"""graft-lint tests: golden trigger + near-miss fixtures per rule R1-R11,
suppression/baseline machinery, the jaxpr auditor + resource ledger
(graft-audit v2), CLI exit codes / JSON format, and the tier-1 gates that
the committed tree is clean modulo lint_baseline.json and that the
committed .jaxpr_ledger.json matches the tree exactly.

Fixture sources are written into tmp_path trees that mimic the repo layout
(rule scopes are path-based), never into the repo itself.  The registry is
traced ONCE per test module (``traced_registry``) and shared by the audit,
ledger and wall-clock tests — tracing dominates layer-2 cost.
"""

from __future__ import annotations

import datetime
import json
import pathlib
import textwrap
import time

import pytest

from esac_tpu.lint import run_layer1
from esac_tpu.lint.cli import main as lint_main
from esac_tpu.lint.suppress import Baseline, BaselineEntry, parse_suppressions

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def traced_registry():
    """(traced entries, trace seconds): the shared layer-2 tracing pass."""
    from esac_tpu.lint.jaxpr_audit import trace_entries

    t0 = time.perf_counter()
    traced = trace_entries()
    return traced, time.perf_counter() - t0


def _write(root: pathlib.Path, rel: str, text: str) -> str:
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(text))
    return rel


def _rules(findings) -> list[str]:
    return sorted(f.rule for f in findings)


# --------------------------------------------------------------------------
# R1: module-level jnp constants

def test_r1_trigger_and_near_miss(tmp_path):
    _write(tmp_path, "esac_tpu/constants.py", """\
        import jax.numpy as jnp
        GRID = jnp.zeros((3, 3))
        """)
    _write(tmp_path, "esac_tpu/near_miss.py", """\
        import numpy as np
        import jax.numpy as jnp

        NP_GRID = np.zeros((3, 3))          # numpy at import time is fine

        def inside():
            return jnp.zeros((3, 3))        # function scope is fine
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R1"]
    assert findings[0].path == "esac_tpu/constants.py"


def test_r1_guarded_script_is_exempt(tmp_path):
    # The generalization.py pattern: a module-level script that forces CPU
    # on line 1 may build arrays at import time — they land on CPU.
    _write(tmp_path, "experiments/sweep.py", """\
        import jax; jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        GRID = jnp.zeros((3, 3))
        """)
    assert run_layer1(tmp_path) == []


def test_r1_guard_inside_function_does_not_exempt(tmp_path):
    # A force-CPU call buried in main() never runs at import time, so it
    # cannot make a module-level array constant safe — but it DOES satisfy
    # R6 (the script forces CPU before first device use when run).
    _write(tmp_path, "tools/late_guard.py", """\
        import jax
        import jax.numpy as jnp

        X = jnp.zeros(3)

        def main():
            jax.config.update("jax_platforms", "cpu")
            print(jax.devices())
        """)
    assert _rules(run_layer1(tmp_path)) == ["R1"]


def test_r1_function_defaults_run_at_import(tmp_path):
    _write(tmp_path, "esac_tpu/defaults.py", """\
        import jax.numpy as jnp

        def f(x=jnp.eye(3)):
            return x
        """)
    assert _rules(run_layer1(tmp_path)) == ["R1"]


# --------------------------------------------------------------------------
# R2: raw norm / bare sqrt in differentiated geometry

def test_r2_trigger_and_near_miss(tmp_path):
    _write(tmp_path, "esac_tpu/geometry/bad.py", """\
        import jax.numpy as jnp

        def normalize(v):
            return v / jnp.linalg.norm(v, axis=-1, keepdims=True)

        def dist(x):
            return jnp.sqrt(jnp.sum(x * x))
        """)
    _write(tmp_path, "esac_tpu/geometry/good.py", """\
        import jax.numpy as jnp
        from esac_tpu.utils.num import safe_norm

        _SQRT_EPS = 1e-18

        def normalize(v):
            return v / safe_norm(v)[..., None]

        def dist(x):
            return jnp.sqrt(jnp.sum(x * x) + 1e-12)   # eps inside the sqrt

        def cdist(z):
            return jnp.sqrt(z + _SQRT_EPS)             # named eps
        """)
    _write(tmp_path, "esac_tpu/data/outside_scope.py", """\
        import jax.numpy as jnp

        def n(v):
            return jnp.linalg.norm(v)
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R2", "R2"]
    assert all(f.path == "esac_tpu/geometry/bad.py" for f in findings)


# --------------------------------------------------------------------------
# R3: scalar-loop linalg reachable from jit/vmap

def test_r3_trigger_and_near_miss(tmp_path):
    _write(tmp_path, "esac_tpu/ransac/solver.py", """\
        import jax
        import jax.numpy as jnp

        def _helper(A, b):
            return jnp.linalg.solve(A, b)      # reachable via hot() -> R3

        @jax.jit
        def hot(A, b):
            return _helper(A, b)

        def cold(A, b):
            return jnp.linalg.svd(A)           # never jitted/vmapped: no R3
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R3"]
    assert "solve" in findings[0].text


def test_r3_sees_the_repo_shard_map_alias(tmp_path):
    # Every shard_map in the package goes through the parallel.mesh compat
    # alias; R3 must treat it as a hot-path root exactly like jax.shard_map.
    _write(tmp_path, "esac_tpu/parallel/sharded.py", """\
        from functools import partial

        import jax.numpy as jnp
        from esac_tpu.parallel.mesh import shard_map

        @partial(shard_map, mesh=None, in_specs=(), out_specs=())
        def local_step(A, b):
            return jnp.linalg.solve(A, b)
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R3"]
    assert "solve" in findings[0].text


def test_r3_vmap_callsite_roots_and_cross_module(tmp_path):
    _write(tmp_path, "esac_tpu/geometry/alg.py", """\
        import jax.numpy as jnp

        def invert(A):
            return jnp.linalg.inv(A)
        """)
    _write(tmp_path, "esac_tpu/ransac/driver.py", """\
        import jax
        from esac_tpu.geometry.alg import invert

        def run(As):
            return jax.vmap(lambda A: invert(A))(As)
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R3"]
    assert findings[0].path == "esac_tpu/geometry/alg.py"


# --------------------------------------------------------------------------
# R4: unpinned contractions in precision-pinned modules

def test_r4_trigger_and_near_miss(tmp_path):
    _write(tmp_path, "esac_tpu/geometry/rot.py", """\
        import jax.numpy as jnp

        def compose(a, b):
            return jnp.matmul(a, b)

        def compose_op(a, b):
            return a @ b
        """)
    _write(tmp_path, "esac_tpu/geometry/rot_ok.py", """\
        import jax
        import jax.numpy as jnp

        def compose(a, b):
            return jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)
        """)
    _write(tmp_path, "esac_tpu/models/net.py", """\
        import jax.numpy as jnp

        def dense(a, b):
            return jnp.matmul(a, b)    # CNN-side module: not pinned scope
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R4", "R4"]
    assert all(f.path == "esac_tpu/geometry/rot.py" for f in findings)


# --------------------------------------------------------------------------
# R5: config dataclasses must be frozen

def test_r5_trigger_and_near_miss(tmp_path):
    _write(tmp_path, "esac_tpu/confs.py", """\
        import dataclasses
        from dataclasses import dataclass

        @dataclass
        class BadConfig:
            n: int = 1

        @dataclasses.dataclass(frozen=True)
        class GoodConfig:
            n: int = 1

        @dataclass
        class Frame:            # not a *Config: data record, no static-arg use
            n: int = 1
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R5"]
    assert "BadConfig" in findings[0].message


# --------------------------------------------------------------------------
# R6: force-CPU guard in ad-hoc scripts

def test_r6_trigger_and_near_misses(tmp_path):
    _write(tmp_path, "tools/bad_tool.py", """\
        import jax

        def main():
            print(jax.devices())
        """)
    _write(tmp_path, "tools/good_tool.py", """\
        import jax

        jax.config.update("jax_platforms", "cpu")

        def main():
            print(jax.devices())
        """)
    _write(tmp_path, "tools/stdlib_tool.py", """\
        import json

        def main():
            print(json.dumps({}))
        """)
    _write(tmp_path, "esac_tpu/library.py", """\
        import jax                 # library module: R6 is script-scope only
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R6"]
    assert findings[0].path == "tools/bad_tool.py"


def test_r6_esac_tpu_import_counts_as_jax_adjacent(tmp_path):
    _write(tmp_path, "experiments/probe.py", """\
        from esac_tpu.ransac import RansacConfig
        """)
    assert _rules(run_layer1(tmp_path)) == ["R6"]


# --------------------------------------------------------------------------
# R8: donation safety

def test_r8_donated_batch_reused_across_loop_is_the_pr4_bug(tmp_path):
    # Faithful reconstruction of the PR-4 bench bug: a donating bucket fn
    # driven in a timing loop with ONE staged batch tree.  On accelerators
    # the first dispatch invalidates the tree; every later iteration reads
    # freed buffers.
    _write(tmp_path, "bench_fixture.py", """\
        import jax

        def make_bucket_fn(cfg):
            def run(params, batch):
                return batch
            donate = (1,) if cfg else ()
            return jax.jit(run, donate_argnums=donate)

        def timed(params, stage):
            fn = make_bucket_fn(True)
            batch = stage()
            for _ in range(10):
                out = fn(params, batch)
            return out
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R8"]
    assert "loop" in findings[0].message or "iteration" in findings[0].message


def test_r8_fresh_tree_per_call_is_the_sanctioned_pattern(tmp_path):
    # The shipped bench.py fix: restage a fresh device tree every call.
    _write(tmp_path, "bench_ok.py", """\
        import jax

        def timed(params, stage):
            fn = jax.jit(lambda p, b: b, donate_argnums=(1,))
            for _ in range(10):
                out = fn(params, stage())
            return out

        def restaged_inside(params, stage):
            fn = jax.jit(lambda p, b: b, donate_argnums=(1,))
            for _ in range(10):
                batch = stage()            # restaged within the loop body
                out = fn(params, batch)
            return out

        def undonated(params, batch):
            fn = jax.jit(lambda p, b: b)   # no donation: reuse is fine
            for _ in range(10):
                out = fn(params, batch)
            return out
        """)
    assert run_layer1(tmp_path) == []


def test_r8_use_after_donation(tmp_path):
    _write(tmp_path, "tools/use_after.py", """\
        import jax
        jax.config.update("jax_platforms", "cpu")

        def once(params, batch):
            fn = jax.jit(lambda p, b: b, donate_argnums=(1,))
            out = fn(params, batch)
            return out, batch["image"]     # read after donation
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R8"]
    assert "after" in findings[0].message


def test_r8_donating_a_cached_registry_tree(tmp_path):
    _write(tmp_path, "esac_tpu/serve_glue.py", """\
        import jax

        def dispatch(registry, entry, batch):
            fn = jax.jit(lambda p, b: b, donate_argnums=(0,))
            params = registry.cache.get(entry)
            return fn(params, batch)
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R8"]
    assert "cache" in findings[0].message


def test_r8_multiline_call_and_restage_are_near_misses(tmp_path):
    # Black-style formatting puts the donated argument's own load BELOW the
    # call's opening line — that is not a reuse; and a tree explicitly
    # restaged after the donating call is a NEW buffer, so a later load of
    # the rebound name is fine (reaching-def cutoff).
    _write(tmp_path, "bench_fmt.py", """\
        import jax

        def multiline(params, batch):
            fn = jax.jit(lambda p, b: b, donate_argnums=(1,))
            out = fn(
                params,
                batch,
            )
            return out

        def restaged(params, stage):
            fn = jax.jit(lambda p, b: b, donate_argnums=(1,))
            batch = stage()
            out = fn(params, batch)
            batch = stage()              # fresh buffers from here on
            return out, batch["image"]
        """)
    assert run_layer1(tmp_path) == []


def test_r8_tuple_unpack_and_for_target_restaging_are_near_misses(tmp_path):
    # `batch, labels = next(it)` and `for batch in it:` both rebind the
    # donated name every iteration — restaging, not reuse.
    _write(tmp_path, "bench_unpack.py", """\
        import jax

        def unpacked(params, it):
            fn = jax.jit(lambda p, b: b, donate_argnums=(1,))
            for i in it:
                batch, labels = i
                out = fn(params, batch)
            return out

        def for_target(params, batches):
            fn = jax.jit(lambda p, b: b, donate_argnums=(1,))
            for batch in batches:
                out = fn(params, batch)
            return out
        """)
    assert run_layer1(tmp_path) == []


def test_r8_tests_are_out_of_scope(tmp_path):
    _write(tmp_path, "tests/test_adversarial.py", """\
        import jax

        def test_donation_crash():
            fn = jax.jit(lambda b: b, donate_argnums=(0,))
            batch = {"x": 1}
            for _ in range(2):
                fn(batch)                  # deliberate, under test
        """)
    assert run_layer1(tmp_path) == []


# --------------------------------------------------------------------------
# R9: retrace safety

def test_r9_jit_in_loop_and_immediate_invocation(tmp_path):
    _write(tmp_path, "esac_tpu/retrace.py", """\
        import jax

        def per_item(xs):
            for x in xs:
                f = jax.jit(lambda v: v + 1)     # fresh wrapper per pass
                x = f(x)
            return x

        def inline(x):
            return jax.jit(lambda v: v * 2)(x)   # build + call + discard
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R9", "R9"]
    assert "loop" in findings[0].message
    assert "fresh program" in findings[1].message


def test_r9_jit_inline_inside_loop_reports_once(tmp_path):
    # jax.jit(f)(x) inside a loop is ONE hazard: the inner maker call
    # carries the jit-in-loop finding, the outer invoke must not add a
    # second report for the same expression.
    _write(tmp_path, "esac_tpu/retrace_loop.py", """\
        import jax

        def per_item(xs):
            for x in xs:
                x = jax.jit(lambda v: v + 1)(x)
            return x
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R9"]
    assert "loop" in findings[0].message


def test_r9_bound_wrappers_are_near_misses(tmp_path):
    _write(tmp_path, "esac_tpu/retrace_ok.py", """\
        from functools import partial

        import jax

        def _impl(x, cfg):
            return x

        # The non-decorator spelling of @partial(jax.jit, ...): the outer
        # call PRODUCES the wrapper (bound once) — not an invocation.
        run = partial(jax.jit, static_argnames=("cfg",))(_impl)

        def make_server():
            return jax.jit(lambda v: v)          # factory: caller binds it

        def profile(x):
            f = jax.jit(lambda v: v)             # bound once, reused below
            for _ in range(3):
                x = f(x)
            return x
        """)
    assert run_layer1(tmp_path) == []


def test_r9_unhashable_literal_in_static_position(tmp_path):
    _write(tmp_path, "esac_tpu/static_args.py", """\
        from functools import partial

        import jax

        @partial(jax.jit, static_argnames=("cfg",))
        def run(x, cfg):
            return x

        def bad_positional(x):
            return run(x, {"n": 1})

        def bad_keyword(x):
            return run(x, cfg=[1, 2])

        def good(x, frozen_cfg):
            return run(x, frozen_cfg)            # hashable static: fine
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R9", "R9"]
    assert all("static" in f.message for f in findings)


def test_r9_scope_is_the_package(tmp_path):
    # Root scripts are one-shot trainers: a single extra trace is not a
    # serving regression, so R9 stays inside esac_tpu/.
    _write(tmp_path, "train_fixture.py", """\
        import jax
        jax.config.update("jax_platforms", "cpu")
        X = jax.jit(lambda v: v)(1.0)
        """)
    assert _rules(run_layer1(tmp_path)) == []


# --------------------------------------------------------------------------
# R10: serve-layer lock discipline

def test_r10_unlocked_touch_of_lock_guarded_state(tmp_path):
    _write(tmp_path, "esac_tpu/serve/racy.py", """\
        import threading

        class RingStats:
            def __init__(self):
                self._lock = threading.Lock()
                self._work = threading.Condition(self._lock)
                self.ring = []
                self.total = 0

            def record(self, x):
                with self._work:          # Condition aliases the lock
                    self.ring.append(x)
                    self.total += 1

            def snapshot(self):
                return list(self.ring)    # unlocked read of guarded state

            def drop(self):
                self.ring.clear()         # unlocked mutation
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R10", "R10"]
    assert {("snapshot" in f.message or "drop" in f.message)
            for f in findings} == {True}
    assert all("ring" in f.message for f in findings)
    # total is only ever touched under the lock: not flagged.
    assert not any("total" in f.message for f in findings)


def test_r10_near_misses(tmp_path):
    _write(tmp_path, "esac_tpu/registry/clean.py", """\
        import threading

        class CleanCache:
            def __init__(self, clock):
                self._lock = threading.Lock()
                self._clock = clock       # immutable post-init
                self.ring = []

            def record(self, x):
                with self._lock:
                    self.ring.append((self._clock(), x))

            def t(self):
                return self._clock()      # unlocked read of immutable state

            def snapshot(self):
                with self._lock:
                    return list(self.ring)

            def _flush_locked(self):
                self.ring.clear()         # helper: every call site locked

            def reset(self):
                with self._lock:
                    self._flush_locked()

        class NoLock:
            def __init__(self):
                self.ring = []

            def record(self, x):
                self.ring.append(x)       # no lock convention: out of scope
        """)
    # The same racy shape OUTSIDE serve/registry is out of R10's scope.
    _write(tmp_path, "esac_tpu/models/racy.py", """\
        import threading

        class Elsewhere:
            def __init__(self):
                self._lock = threading.Lock()
                self.ring = []

            def locked(self):
                with self._lock:
                    self.ring.append(1)

            def unlocked(self):
                self.ring.clear()
        """)
    assert run_layer1(tmp_path) == []


# --------------------------------------------------------------------------
# R11: jaxpr-audit registry coverage gate

def _write_r11_tree(tmp_path):
    _write(tmp_path, "esac_tpu/lint/registry.py", """\
        R11_WAIVED = {
            "waived_fn": "fixture: covered transitively by registered_fn",
        }

        def _build():
            from esac_tpu.ransac.entries import registered_fn
            return registered_fn
        """)
    _write(tmp_path, "esac_tpu/ransac/entries.py", """\
        from functools import partial

        import jax

        @jax.jit
        def registered_fn(x):
            return x

        @partial(jax.jit, static_argnames=())
        def waived_fn(x):
            return x

        @jax.jit
        def rogue_fn(x):
            return x

        @jax.jit
        def _private_helper(x):
            return x

        def make_rogue_factory(c):
            @jax.jit
            def inner(b):
                return b
            return inner

        def make_plain_helper(c):
            return c                       # no jit inside: not an entry
        """)


def test_r11_flags_unregistered_unwaived_entry_points(tmp_path):
    _write_r11_tree(tmp_path)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R11", "R11"]
    flagged = {f.message.split("'")[1] for f in findings}
    assert flagged == {"rogue_fn", "make_rogue_factory"}


def test_r11_skips_trees_without_a_registry(tmp_path):
    # Fixture roots (and downstream checkouts) without lint/registry.py are
    # not audited trees: no coverage gate.
    _write(tmp_path, "esac_tpu/ransac/entries.py", """\
        import jax

        @jax.jit
        def rogue_fn(x):
            return x
        """)
    assert run_layer1(tmp_path) == []


def test_r11_repo_registry_covers_every_discovered_entry_point():
    """The day-one gaps are CLOSED: every public jitted entry point in the
    package is registered (traced + audited + ledgered) or waived with a
    reason — including the two PR-6 registrations."""
    from esac_tpu.lint.ast_rules import _r11_discover, _r11_registry_names

    registered, waived = _r11_registry_names(
        (REPO / "esac_tpu/lint/registry.py").read_text()
    )
    names = {name for _, _, name in _r11_discover(REPO)}
    assert "esac_infer_topk_frames" in names
    assert "make_esac_infer_sharded_frames_dynamic" in names
    assert "esac_infer_topk_frames" in registered
    assert "make_esac_infer_sharded_frames_dynamic" in registered
    uncovered = {n for n in names
                 if n not in registered and n not in waived}
    assert uncovered == set()
    assert all(reason for reason in waived.values()), \
        "every R11 waiver needs a reviewed reason"


# --------------------------------------------------------------------------
# R7: shell timeout/kill around python

def test_r7_trigger_and_near_miss(tmp_path):
    _write(tmp_path, "experiments/bad.sh", """\
        #!/bin/sh
        timeout 600 python train_esac.py --cpu
        kill $TRAINER_PID
        """)
    _write(tmp_path, "experiments/good.sh", """\
        #!/bin/sh
        # never kill the trainer (prose mention is fine)
        while kill -0 $TRAINER_PID 2>/dev/null; do sleep 5; done
        setsid nohup python tools/tpu_probe.py > probe.log 2>&1 &
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R7", "R7"]
    assert all(f.path == "experiments/bad.sh" for f in findings)


# --------------------------------------------------------------------------
# suppressions

def test_inline_suppression_silences_finding(tmp_path):
    _write(tmp_path, "esac_tpu/geometry/sup.py", """\
        import jax.numpy as jnp

        def n(v):
            return jnp.linalg.norm(v)  # graft-lint: disable=R2(fixture reason)
        """)
    assert run_layer1(tmp_path) == []


def test_file_level_suppression(tmp_path):
    _write(tmp_path, "tools/chip_tool.py", """\
        # graft-lint: disable-file=R6(sanctioned chip toucher - fixture)
        import jax
        """)
    assert run_layer1(tmp_path) == []


def test_shell_suppression(tmp_path):
    _write(tmp_path, "experiments/sup.sh", """\
        #!/bin/sh
        kill $PID  # graft-lint: disable=R7(fixture: pid is a sleep, not jax)
        """)
    assert run_layer1(tmp_path) == []


def test_suppression_parser():
    per_line, per_file = parse_suppressions(
        "x = 1  # graft-lint: disable=R1,R4(two rules one line)\n"
        "# graft-lint: disable-file=R6(whole file)\n"
    )
    assert per_line == {1: {"R1", "R4"}}
    assert per_file == {"R6"}


def test_multiline_reason_does_not_widen_suppression():
    # A reason that wraps to the next comment line (unclosed paren) ends the
    # rule list: rule ids mentioned in the prose must not get suppressed.
    per_line, per_file = parse_suppressions(
        "# graft-lint: disable-file=R6(guards R2 and\n"
        "# R3 style issues elsewhere)\n"
    )
    assert per_file == {"R6"}
    assert per_line == {}


# --------------------------------------------------------------------------
# baseline: grandfathering + expiry

def _one_r2_finding(tmp_path):
    _write(tmp_path, "esac_tpu/geometry/base.py", """\
        import jax.numpy as jnp

        def n(v):
            return jnp.linalg.norm(v)
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R2"]
    return findings


def test_baseline_masks_matching_finding(tmp_path):
    findings = _one_r2_finding(tmp_path)
    b = Baseline.from_findings(findings)
    remaining, stale = b.apply(findings)
    assert remaining == [] and stale == []


def test_baseline_is_line_number_independent(tmp_path):
    findings = _one_r2_finding(tmp_path)
    f = findings[0]
    b = Baseline([BaselineEntry(rule=f.rule, path=f.path, text=f.text)])
    # Same offending line, shifted by edits above it: still masked.
    shifted = [type(f)(f.rule, f.path, f.line + 10, f.text, f.message)]
    remaining, stale = b.apply(shifted)
    assert remaining == [] and stale == []


def test_baseline_expiry_resurfaces_finding(tmp_path):
    findings = _one_r2_finding(tmp_path)
    f = findings[0]
    expired = BaselineEntry(rule=f.rule, path=f.path, text=f.text,
                            expires="2026-01-01")
    b = Baseline([expired])
    remaining, stale = b.apply(findings,
                               today=datetime.date(2026, 6, 1))
    assert remaining == findings          # mask no longer applies
    assert stale == [expired]             # and the entry is reported stale
    # Before expiry the same entry still masks.
    remaining, stale = b.apply(findings,
                               today=datetime.date(2025, 12, 1))
    assert remaining == [] and stale == []


def test_baseline_unused_entry_is_stale(tmp_path):
    b = Baseline([BaselineEntry(rule="R2", path="gone.py", text="x = 1")])
    remaining, stale = b.apply([])
    assert remaining == [] and len(stale) == 1


def test_baseline_roundtrip(tmp_path):
    findings = _one_r2_finding(tmp_path)
    path = tmp_path / "baseline.json"
    Baseline.from_findings(findings).write(path)
    loaded = Baseline.load(path)
    remaining, _ = loaded.apply(findings)
    assert remaining == []
    assert json.loads(path.read_text())["entries"]


# --------------------------------------------------------------------------
# CLI exit codes (driver contract: 0 clean, 1 findings, 2 internal error)

def test_cli_exit_1_on_seeded_violations_of_every_rule(tmp_path, capsys):
    _write(tmp_path, "esac_tpu/r1.py",
           "import jax.numpy as jnp\nX = jnp.zeros(3)\n")
    _write(tmp_path, "esac_tpu/geometry/r2.py",
           "import jax.numpy as jnp\n\ndef n(v):\n"
           "    return jnp.linalg.norm(v)\n")
    _write(tmp_path, "esac_tpu/ransac/r3.py",
           "import jax\nimport jax.numpy as jnp\n\n@jax.jit\ndef h(A, b):\n"
           "    return jnp.linalg.solve(A, b)\n")
    _write(tmp_path, "esac_tpu/geometry/r4.py",
           "import jax.numpy as jnp\n\ndef m(a, b):\n"
           "    return jnp.matmul(a, b)\n")
    _write(tmp_path, "esac_tpu/r5.py",
           "from dataclasses import dataclass\n\n@dataclass\n"
           "class LintFixtureConfig:\n    n: int = 1\n")
    _write(tmp_path, "tools/r6.py", "import jax\n")
    _write(tmp_path, "experiments/r7.sh", "timeout 5 python x.py\n")
    rc = lint_main(["--root", str(tmp_path), "--no-jaxpr"])
    out = capsys.readouterr().out
    assert rc == 1
    for rule in ("R1", "R2", "R3", "R4", "R5", "R6", "R7"):
        assert f" {rule} " in out, f"{rule} missing from CLI output:\n{out}"


def test_cli_exit_0_on_clean_tree(tmp_path, capsys):
    _write(tmp_path, "esac_tpu/ok.py", "import numpy as np\nX = np.zeros(3)\n")
    assert lint_main(["--root", str(tmp_path), "--no-jaxpr"]) == 0


def test_cli_exit_2_on_malformed_baseline(tmp_path, capsys):
    # Driver contract: a broken baseline file is an internal error (2),
    # never to be misread as findings (1).
    _write(tmp_path, "esac_tpu/ok.py", "import numpy as np\n")
    bad = tmp_path / "baseline.json"
    bad.write_text('{"entries": [{"rule": "R2", "bogus": 1}]}\n')
    assert lint_main(["--root", str(tmp_path), "--no-jaxpr",
                      "--baseline", str(bad)]) == 2


def _seed_violation(tmp_path):
    return _write(tmp_path, "esac_tpu/geometry/r2.py",
                  "import jax.numpy as jnp\n\ndef n(v):\n"
                  "    return jnp.linalg.norm(v)\n")


def test_cli_json_format_one_object_per_line(tmp_path, capsys):
    _seed_violation(tmp_path)
    rc = lint_main(["--root", str(tmp_path), "--no-jaxpr",
                    "--format", "json"])
    captured = capsys.readouterr()
    assert rc == 1
    lines = captured.out.strip().splitlines()
    assert lines, "findings must ride stdout in json mode"
    objs = [json.loads(line) for line in lines]     # every line parses
    for o in objs:
        assert {"id", "rule", "path", "line", "text", "message"} <= set(o)
        assert o["id"].startswith(o["rule"] + "-")
    # The human summary stays off stdout (driver consumes objects only).
    assert "finding(s) over" not in captured.out
    assert "finding(s) over" in captured.err


def test_cli_json_ids_disambiguate_identical_lines(tmp_path, capsys):
    # Two textually identical violations in one file share the baseline
    # identity (rule, path, text) by design — the json ids must still be
    # unique so a driver tracking resolution state never conflates them.
    _write(tmp_path, "esac_tpu/geometry/twice.py",
           "import jax.numpy as jnp\n\ndef a(v):\n"
           "    return jnp.linalg.norm(v)\n\ndef b(v):\n"
           "    return jnp.linalg.norm(v)\n")
    rc = lint_main(["--root", str(tmp_path), "--no-jaxpr",
                    "--format", "json"])
    ids = [json.loads(l)["id"] for l in
           capsys.readouterr().out.strip().splitlines()]
    assert rc == 1 and len(ids) == 2
    assert len(set(ids)) == 2
    assert ids[1] == ids[0] + "-2"


def test_cli_json_ids_are_stable_and_line_number_independent(tmp_path, capsys):
    rel = _seed_violation(tmp_path)
    lint_main(["--root", str(tmp_path), "--no-jaxpr", "--format", "json"])
    ids1 = [json.loads(l)["id"] for l in
            capsys.readouterr().out.strip().splitlines()]
    # Shift the offending line down: same violation, same id.
    p = tmp_path / rel
    p.write_text("# a new comment line\n" + p.read_text())
    lint_main(["--root", str(tmp_path), "--no-jaxpr", "--format", "json"])
    ids2 = [json.loads(l)["id"] for l in
            capsys.readouterr().out.strip().splitlines()]
    assert ids1 == ids2 and len(ids1) == 1


def test_changed_mode_audits_on_utils_edits():
    # utils/precision.py and utils/num.py carry the invariants the jaxpr
    # audit enforces; a --changed run touching them must include layer 2.
    # The resource ledger rides the SAME condition (the ~20s tracing pass
    # is skipped unless a traced package file changed).
    from esac_tpu.lint.cli import _audit_needed

    assert _audit_needed(["esac_tpu/utils/precision.py"])
    assert _audit_needed(["esac_tpu/utils/num.py"])
    assert _audit_needed(None)      # full-tree runs always trace + ledger
    assert not _audit_needed(["tools/eval_agreement.py", "LINT.md"])
    assert not _audit_needed(["bench.py", "tests/test_serve.py"])


def test_cli_write_baseline_then_clean(tmp_path, capsys):
    _write(tmp_path, "esac_tpu/geometry/r2.py",
           "import jax.numpy as jnp\n\ndef n(v):\n"
           "    return jnp.linalg.norm(v)\n")
    base = tmp_path / "baseline.json"
    assert lint_main(["--root", str(tmp_path), "--no-jaxpr",
                      "--baseline", str(base), "--write-baseline"]) == 0
    assert lint_main(["--root", str(tmp_path), "--no-jaxpr",
                      "--baseline", str(base)]) == 0


def test_cli_write_baseline_refuses_scoped_runs(tmp_path, capsys):
    # A scoped --write-baseline would replace the whole file with the
    # slice's findings, deleting every entry for unscanned files.
    rel = _write(tmp_path, "esac_tpu/geometry/r2.py",
                 "import jax.numpy as jnp\n\ndef n(v):\n"
                 "    return jnp.linalg.norm(v)\n")
    base = tmp_path / "baseline.json"
    assert lint_main(["--root", str(tmp_path), "--no-jaxpr",
                      "--baseline", str(base), "--write-baseline", rel]) == 2
    assert not base.exists()


# --------------------------------------------------------------------------
# layer 2: jaxpr auditor

def test_audit_flags_unpinned_dot_in_pinned_graph():
    import jax
    import jax.numpy as jnp

    from esac_tpu.lint.jaxpr_audit import audit_jaxpr

    a = jnp.zeros((3, 3))
    closed = jax.make_jaxpr(lambda x, y: jnp.matmul(x, y))(a, a)
    findings = audit_jaxpr("fixture", closed, pinned=True)
    assert [f.rule for f in findings] == ["J3"]
    # The identical trace in an unpinned graph is fine.
    assert audit_jaxpr("fixture", closed, pinned=False) == []


def test_audit_accepts_hmm():
    import jax
    import jax.numpy as jnp

    from esac_tpu.lint.jaxpr_audit import audit_jaxpr
    from esac_tpu.utils.precision import hmm

    a = jnp.zeros((3, 3))
    closed = jax.make_jaxpr(hmm)(a, a)
    assert audit_jaxpr("fixture", closed, pinned=True) == []


def test_audit_flags_while_loop():
    import jax

    from esac_tpu.lint.jaxpr_audit import audit_jaxpr

    def dynamic_trip(x):
        return jax.lax.while_loop(
            lambda v: v[0] < 8, lambda v: (v[0] + 1, v[1] * 0.5), (0, x)
        )[1]

    closed = jax.make_jaxpr(dynamic_trip)(1.0)
    findings = audit_jaxpr("fixture", closed, pinned=False)
    assert any(f.rule == "J1" and f.text == "while" for f in findings)


def test_audit_recurses_into_scan_and_jit():
    import jax
    import jax.numpy as jnp

    from esac_tpu.lint.jaxpr_audit import audit_jaxpr

    @jax.jit
    def scanned(x):
        def body(carry, _):
            return jnp.matmul(carry, carry), None

        out, _ = jax.lax.scan(body, x, None, length=3)
        return out

    closed = jax.make_jaxpr(scanned)(jnp.eye(3))
    findings = audit_jaxpr("fixture", closed, pinned=True)
    assert [f.rule for f in findings] == ["J3"]  # found inside scan-in-pjit


def test_registered_entry_points_audit_clean(traced_registry):
    """The acceptance gate: every registry entry traces on CPU with zero
    disallowed primitives, static shapes, and pinned call graphs at
    HIGHEST/f32 — the jaxpr-level form of the CLAUDE.md conventions."""
    from esac_tpu.lint.jaxpr_audit import run_audit

    traced, _ = traced_registry
    findings = run_audit(traced=traced)
    assert findings == [], "\n".join(f.format() for f in findings)


# --------------------------------------------------------------------------
# layer 2b: the jaxpr resource ledger (graft-audit v2)

def _mini_stats(nbytes, flops, census):
    return {
        "pinned": True, "flops": flops, "peak_intermediate_bytes": nbytes,
        "dot_general_count": sum(census.values()), "dot_census": census,
        "top_intermediates": [],
    }


def test_ledger_entry_stats_census_flops_and_peak():
    import jax
    import jax.numpy as jnp

    from esac_tpu.lint.ledger import entry_stats
    from esac_tpu.utils.precision import hmm

    a = jnp.zeros((4, 4))
    s = entry_stats(jax.make_jaxpr(lambda x: hmm(x, x) + 1.0)(a))
    assert s["dot_census"] == {"HIGHEST:float32": 1}
    assert s["dot_general_count"] == 1
    assert s["flops"] >= 2 * 4 * 4 * 4          # the contraction dominates
    assert s["peak_intermediate_bytes"] >= 2 * 4 * 4 * 4  # dot out + add out
    assert s["top_intermediates"][0]["bytes"] == 64
    # The identical trace through a default-precision matmul flips the
    # census key — exactly the signal the pin-drop gate diffs on.
    s2 = entry_stats(jax.make_jaxpr(lambda x: jnp.matmul(x, x) + 1.0)(a))
    assert list(s2["dot_census"]) == ["DEFAULT:float32"]


def test_ledger_flops_multiply_scan_trip_counts():
    import jax
    import jax.numpy as jnp

    from esac_tpu.lint.ledger import entry_stats

    def f(x):
        def body(c, _):
            return c * 2.0, None

        out, _ = jax.lax.scan(body, x, None, length=5)
        return out

    s = entry_stats(jax.make_jaxpr(f)(jnp.zeros((8,))))
    assert s["flops"] >= 5 * 8      # body flops x trip count


def test_ledger_roundtrip(tmp_path):
    from esac_tpu.lint.ledger import diff_ledger, load_ledger, write_ledger

    entries = {"e": _mini_stats(1000, 2000, {"HIGHEST:float32": 3})}
    path = tmp_path / "ledger.json"
    write_ledger(path, entries)
    loaded = load_ledger(path)
    findings, stale = diff_ledger(loaded, entries)
    assert findings == [] and stale == []
    assert load_ledger(tmp_path / "missing.json") is None


def test_ledger_diff_fails_on_materialization_regression():
    from esac_tpu.lint.ledger import diff_ledger

    old = {"e": _mini_stats(1000, 1000, {"HIGHEST:float32": 3})}
    # 2x peak bytes: the "silently doubles an entry's materialization" case.
    doubled = {"e": _mini_stats(2000, 1000, {"HIGHEST:float32": 3})}
    findings, _ = diff_ledger(old, doubled)
    assert [f.rule for f in findings] == ["J4"]
    assert "peak_intermediate_bytes" in findings[0].text
    # Within tolerance: no failure, but the drift is reported stale.
    nudged = {"e": _mini_stats(1100, 1000, {"HIGHEST:float32": 3})}
    findings, stale = diff_ledger(old, nudged)
    assert findings == [] and len(stale) == 1
    # Improvement: never a failure, still stale (regenerate + review).
    better = {"e": _mini_stats(500, 500, {"HIGHEST:float32": 3})}
    findings, stale = diff_ledger(old, better)
    assert findings == [] and len(stale) == 1


def test_ledger_diff_fails_on_dropped_highest_pin():
    from esac_tpu.lint.ledger import diff_ledger

    old = {"e": _mini_stats(1000, 1000,
                            {"HIGHEST:float32": 3, "DEFAULT:float32": 2})}
    new = {"e": _mini_stats(1000, 1000,
                            {"HIGHEST:float32": 2, "DEFAULT:float32": 3})}
    findings, _ = diff_ledger(old, new)
    assert [f.rule for f in findings] == ["J4"]
    assert "HIGHEST" in findings[0].message
    # Adding a NEW unpinned dot without losing a pin is census drift
    # (stale), not a pin drop — the bytes/flops gates cover real growth.
    grown = {"e": _mini_stats(1000, 1000,
                              {"HIGHEST:float32": 3, "DEFAULT:float32": 3})}
    findings, stale = diff_ledger(old, grown)
    assert findings == [] and len(stale) == 1


def test_ledger_diff_missing_and_stale_entries():
    from esac_tpu.lint.ledger import diff_ledger

    stats = _mini_stats(1000, 1000, {"HIGHEST:float32": 3})
    # New entry with no committed record: fail (the coverage gate's ledger
    # sibling) — except when the entry was skipped as untraceable.
    findings, stale = diff_ledger({}, {"new": stats})
    assert [f.rule for f in findings] == ["J4"]
    assert "missing-entry" in findings[0].text
    # Committed entry whose registry entry is gone: stale, not a failure.
    findings, stale = diff_ledger({"gone": stats}, {})
    assert findings == [] and len(stale) == 1
    # Skipped (untraceable in this process): neither failure nor stale.
    findings, stale = diff_ledger({"mesh_entry": stats}, {}, {"mesh_entry"})
    assert findings == [] and stale == []


def test_cli_ledger_gate_exits_1_on_materialization_regression(
    tmp_path, monkeypatch, capsys
):
    """End-to-end form of the diff gate: a committed ledger recording HALF
    the current peak bytes (i.e. the tree silently doubled an entry's
    materialization) must fail the CLI with exit 1 and a J4 finding; the
    honest committed ledger exits 0."""
    import jax
    import jax.numpy as jnp

    import esac_tpu.lint.jaxpr_audit as audit_mod
    from esac_tpu.lint.ledger import LEDGER_NAME, build_ledger, write_ledger
    from esac_tpu.lint.registry import Entry

    closed = jax.make_jaxpr(lambda x: x @ x + 1.0)(jnp.zeros((4, 4)))
    fake = [(Entry("fixture_entry", pinned=False, build=lambda: None), closed)]
    monkeypatch.setattr(audit_mod, "trace_entries", lambda entries=None: fake)
    _write(tmp_path, "esac_tpu/ok.py", "import numpy as np\n")

    current, _ = build_ledger(fake)
    write_ledger(tmp_path / LEDGER_NAME, current)
    assert lint_main(["--root", str(tmp_path)]) == 0

    doctored = {
        name: {**stats, "peak_intermediate_bytes":
               stats["peak_intermediate_bytes"] // 2}
        for name, stats in current.items()
    }
    write_ledger(tmp_path / LEDGER_NAME, doctored)
    rc = lint_main(["--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert " J4 " in out and "peak_intermediate_bytes" in out


def test_committed_ledger_matches_tree_exactly(traced_registry):
    """The tier-1 ledger gate: the committed .jaxpr_ledger.json equals the
    recomputed ledger bit-for-bit (tracing is deterministic on this
    container) — any drift means regenerate-and-review, any regression
    means exit 1 (diff gate)."""
    from esac_tpu.lint.ledger import (
        LEDGER_NAME,
        build_ledger,
        diff_ledger,
        load_ledger,
    )

    traced, _ = traced_registry
    current, skipped = build_ledger(traced)
    committed = load_ledger(REPO / LEDGER_NAME)
    assert committed is not None, "no committed ledger: run --write-ledger"
    findings, stale = diff_ledger(committed, current, skipped)
    assert findings == [], "\n".join(f.format() for f in findings)
    assert stale == [], "\n".join(stale)
    current_json = json.loads(json.dumps(current))
    for name, cur in current_json.items():
        assert committed.get(name) == cur, f"ledger drift in {name}"


def test_committed_hazard_census_matches_tree_exactly(traced_registry):
    """graft-audit v4's exact-match gate, the J5 analog of the
    ledger/lock-graph assertions: every grad-registered entry carries a
    committed grad_hazards census, committed == recomputed exactly, and
    the ONLY unguarded domain-edge site across every backward jaxpr is
    the reviewed focal-length division in geometry/pnp.py bearings — the
    same site the one R14 suppression covers, so the static, jaxpr and
    suppression layers all tell one story."""
    from esac_tpu.lint.ledger import (
        LEDGER_NAME,
        grad_hazard_census,
        load_ledger,
    )
    from esac_tpu.lint.registry import ENTRIES

    committed = load_ledger(REPO / LEDGER_NAME)
    grad_entries = {e.name for e in ENTRIES if e.grad}
    assert len(grad_entries) >= 8, "grad-registered entry set shrank"
    traced, _ = traced_registry
    by_name = {e.name: closed for e, closed in traced}
    for name in sorted(grad_entries):
        rec = committed[name]
        assert rec.get("grad") is True, name
        census = grad_hazard_census(by_name[name])
        assert rec.get("grad_hazards") == census, f"census drift in {name}"
        unguarded = {
            prim: c["unguarded"] for prim, c in census.items()
            if c["unguarded"]
        }
        # The reviewed residual: at most the single focal division per
        # entry (entries whose trace reaches bearings), nothing else.
        assert unguarded in ({}, {"div": 1}), (name, unguarded)
    # Non-grad entries must NOT carry a census (forward-only traces have
    # no backward to walk — a census there would be a lie).
    for name, rec in committed.items():
        if name not in grad_entries:
            assert "grad_hazards" not in rec, name


def test_committed_ledger_quantifies_the_scoring_errmap():
    """DESIGN.md §9's errmap claim as a committed number — ISSUE 8 flipped
    its sign on the inference side: every INFERENCE entry records the
    would-be errmap footprint with ``present_in_trace`` FALSE (scoring +
    selection stream through score_chunk tiles; the fusion evidence), and
    only the materializing TRAINING record (scoring_errmap_grad) keeps a
    true presence bit."""
    from esac_tpu.lint.ledger import _ERRMAP_DIMS, LEDGER_NAME, load_ledger

    committed = load_ledger(REPO / LEDGER_NAME)
    for name, dims in _ERRMAP_DIMS.items():
        e = committed[name]["errmap"]
        assert e["trace_dims"] == dims
        want = 4
        for d in dims.values():
            want *= d
        assert e["bytes_at_trace_shapes"] == want, name
        if name == "scoring_errmap_grad":
            assert e["present_in_trace"] is True, name
        else:
            assert e["present_in_trace"] is False, (
                f"{name}: the errmap rematerialized on an inference entry "
                "(the ISSUE 8 fusion regressed)"
            )
    # And the entry-level peaks the fusion argument needs are committed.
    for name in ("esac_infer_frames", "dsac_infer_fused_select",
                 "scoring_errmap_grad"):
        entry = committed[name]
        assert entry["peak_intermediate_bytes"] > 0
        assert entry["flops"] > 0
        assert entry["dot_census"]


def test_lint_wall_clock_recorded_and_inside_budget(traced_registry):
    """Record the lint gate's own wall clock in .tier1_wall.json (merged —
    conftest preserves foreign keys) so the tier-1 budget math is visible:
    layer 1 + one shared tracing pass must stay a small fraction of 870s.
    run_layer1 now INCLUDES the graft-audit v3 lock-graph pass (R12/R13
    over the fleet scope), and the committed-graph diff is timed
    explicitly below — the lock-graph wall clock folds into the same
    lint_wall_s record, budget assertion intact."""
    from esac_tpu.lint.lockgraph import (
        LOCK_GRAPH_NAME,
        build_graph,
        diff_graph,
        load_graph,
    )

    from esac_tpu.lint import faultflow

    _, trace_s = traced_registry
    t0 = time.perf_counter()
    run_layer1(REPO)
    committed = load_graph(REPO / LOCK_GRAPH_NAME)
    if committed is not None:
        diff_graph(committed, build_graph(REPO))
    committed_tax = faultflow.load_taxonomy(
        REPO / faultflow.FAULT_TAXONOMY_NAME)
    if committed_tax is not None:
        faultflow.diff_taxonomy(committed_tax, faultflow.build_taxonomy(REPO))
    layer1_s = time.perf_counter() - t0
    total = trace_s + layer1_s
    wall_file = REPO / ".tier1_wall.json"
    record = {}
    if wall_file.exists():
        try:
            record = json.loads(wall_file.read_text())
        except (OSError, ValueError):
            record = {}
    record["lint_wall_s"] = round(total, 1)
    wall_file.write_text(json.dumps(record))
    assert total < 240, (
        f"lint gate took {total:.0f}s — too large a share of the 870s "
        "tier-1 budget; trim the registry trace shapes"
    )


# --------------------------------------------------------------------------
# graft-audit v3: the committed lock-graph gate (tests/test_lockgraph.py
# carries the fixture-level R12/R13 and witness coverage)

def test_committed_lock_graph_matches_tree_exactly():
    """The tier-1 lock-graph gate, ledger-style: the committed
    .lock_graph.json equals the recomputed fleet analysis exactly — any
    drift means regenerate-and-review (--write-lock-graph), any
    unreviewed new edge means exit 1 (R12 diff gate)."""
    from esac_tpu.lint.lockgraph import (
        LOCK_GRAPH_NAME,
        build_graph,
        diff_graph,
        load_graph,
    )

    current = build_graph(REPO)
    committed = load_graph(REPO / LOCK_GRAPH_NAME)
    assert committed is not None, \
        "no committed lock graph: run `python -m esac_tpu.lint " \
        "--write-lock-graph` and review the edges"
    findings, stale = diff_graph(committed, current)
    assert findings == [], "\n".join(f.format() for f in findings)
    assert stale == [], "\n".join(stale)
    assert committed == json.loads(json.dumps(current)), \
        "lock graph drift: regenerate with --write-lock-graph and review"


def test_changed_mode_lock_pass_rides_fleet_and_lint_edits():
    """--changed skips the lock-graph pass unless a fleet
    (serve/registry/obs) or lint file changed — the jaxpr-layer skip
    mirrored (satellite of ISSUE 11)."""
    from esac_tpu.lint.lockgraph import lock_pass_needed

    assert lock_pass_needed(None)
    assert lock_pass_needed(["esac_tpu/serve/slo.py"])
    assert lock_pass_needed(["esac_tpu/lint/registry.py"])
    assert not lock_pass_needed(
        ["esac_tpu/utils/num.py", "bench.py", "DESIGN.md"]
    )


# --------------------------------------------------------------------------
# graft-audit v5: the committed fault-taxonomy gate (tests/
# test_faultflow.py carries the fixture-level R16/R17/R18 and outcome-
# witness coverage plus the member-by-member repo pins)

def test_committed_fault_taxonomy_matches_tree_exactly():
    """The tier-1 fault-taxonomy gate, ledger-style: the committed
    .fault_taxonomy.json equals the recomputed fleet fault-flow
    analysis exactly — any drift means regenerate-and-review
    (--write-fault-taxonomy), any unreviewed new error class or
    raise->outcome edge means exit 1 (R16 diff gate)."""
    from esac_tpu.lint.faultflow import (
        FAULT_TAXONOMY_NAME,
        build_taxonomy,
        diff_taxonomy,
        load_taxonomy,
    )

    current = build_taxonomy(REPO)
    committed = load_taxonomy(REPO / FAULT_TAXONOMY_NAME)
    assert committed is not None, \
        "no committed fault taxonomy: run `python -m esac_tpu.lint " \
        "--write-fault-taxonomy` and review the catalog"
    findings, stale = diff_taxonomy(committed, current)
    assert findings == [], "\n".join(f.format() for f in findings)
    assert stale == [], "\n".join(stale)
    assert committed == json.loads(json.dumps(current)), \
        "fault taxonomy drift: regenerate with --write-fault-taxonomy " \
        "and review"


# --------------------------------------------------------------------------
# the tier-1 gate: committed tree clean modulo baseline

def test_committed_tree_is_clean_modulo_baseline():
    findings = run_layer1(REPO)
    baseline = Baseline.load(REPO / "lint_baseline.json")
    remaining, _ = baseline.apply(findings)
    assert remaining == [], "\n".join(f.format() for f in remaining)


def test_committed_baseline_has_no_stale_entries():
    findings = run_layer1(REPO)
    baseline = Baseline.load(REPO / "lint_baseline.json")
    _, stale = baseline.apply(findings)
    assert stale == [], f"stale baseline entries: {stale}"
