"""graft-lint tests: golden trigger + near-miss fixtures per rule R1-R7,
suppression/baseline machinery, the jaxpr auditor, CLI exit codes, and the
tier-1 gate that the committed tree is clean modulo lint_baseline.json.

Fixture sources are written into tmp_path trees that mimic the repo layout
(rule scopes are path-based), never into the repo itself.
"""

from __future__ import annotations

import datetime
import json
import pathlib
import textwrap

import pytest

from esac_tpu.lint import run_layer1
from esac_tpu.lint.cli import main as lint_main
from esac_tpu.lint.suppress import Baseline, BaselineEntry, parse_suppressions

REPO = pathlib.Path(__file__).resolve().parent.parent


def _write(root: pathlib.Path, rel: str, text: str) -> str:
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(text))
    return rel


def _rules(findings) -> list[str]:
    return sorted(f.rule for f in findings)


# --------------------------------------------------------------------------
# R1: module-level jnp constants

def test_r1_trigger_and_near_miss(tmp_path):
    _write(tmp_path, "esac_tpu/constants.py", """\
        import jax.numpy as jnp
        GRID = jnp.zeros((3, 3))
        """)
    _write(tmp_path, "esac_tpu/near_miss.py", """\
        import numpy as np
        import jax.numpy as jnp

        NP_GRID = np.zeros((3, 3))          # numpy at import time is fine

        def inside():
            return jnp.zeros((3, 3))        # function scope is fine
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R1"]
    assert findings[0].path == "esac_tpu/constants.py"


def test_r1_guarded_script_is_exempt(tmp_path):
    # The generalization.py pattern: a module-level script that forces CPU
    # on line 1 may build arrays at import time — they land on CPU.
    _write(tmp_path, "experiments/sweep.py", """\
        import jax; jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        GRID = jnp.zeros((3, 3))
        """)
    assert run_layer1(tmp_path) == []


def test_r1_guard_inside_function_does_not_exempt(tmp_path):
    # A force-CPU call buried in main() never runs at import time, so it
    # cannot make a module-level array constant safe — but it DOES satisfy
    # R6 (the script forces CPU before first device use when run).
    _write(tmp_path, "tools/late_guard.py", """\
        import jax
        import jax.numpy as jnp

        X = jnp.zeros(3)

        def main():
            jax.config.update("jax_platforms", "cpu")
            print(jax.devices())
        """)
    assert _rules(run_layer1(tmp_path)) == ["R1"]


def test_r1_function_defaults_run_at_import(tmp_path):
    _write(tmp_path, "esac_tpu/defaults.py", """\
        import jax.numpy as jnp

        def f(x=jnp.eye(3)):
            return x
        """)
    assert _rules(run_layer1(tmp_path)) == ["R1"]


# --------------------------------------------------------------------------
# R2: raw norm / bare sqrt in differentiated geometry

def test_r2_trigger_and_near_miss(tmp_path):
    _write(tmp_path, "esac_tpu/geometry/bad.py", """\
        import jax.numpy as jnp

        def normalize(v):
            return v / jnp.linalg.norm(v, axis=-1, keepdims=True)

        def dist(x):
            return jnp.sqrt(jnp.sum(x * x))
        """)
    _write(tmp_path, "esac_tpu/geometry/good.py", """\
        import jax.numpy as jnp
        from esac_tpu.utils.num import safe_norm

        _SQRT_EPS = 1e-18

        def normalize(v):
            return v / safe_norm(v)[..., None]

        def dist(x):
            return jnp.sqrt(jnp.sum(x * x) + 1e-12)   # eps inside the sqrt

        def cdist(z):
            return jnp.sqrt(z + _SQRT_EPS)             # named eps
        """)
    _write(tmp_path, "esac_tpu/data/outside_scope.py", """\
        import jax.numpy as jnp

        def n(v):
            return jnp.linalg.norm(v)
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R2", "R2"]
    assert all(f.path == "esac_tpu/geometry/bad.py" for f in findings)


# --------------------------------------------------------------------------
# R3: scalar-loop linalg reachable from jit/vmap

def test_r3_trigger_and_near_miss(tmp_path):
    _write(tmp_path, "esac_tpu/ransac/solver.py", """\
        import jax
        import jax.numpy as jnp

        def _helper(A, b):
            return jnp.linalg.solve(A, b)      # reachable via hot() -> R3

        @jax.jit
        def hot(A, b):
            return _helper(A, b)

        def cold(A, b):
            return jnp.linalg.svd(A)           # never jitted/vmapped: no R3
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R3"]
    assert "solve" in findings[0].text


def test_r3_sees_the_repo_shard_map_alias(tmp_path):
    # Every shard_map in the package goes through the parallel.mesh compat
    # alias; R3 must treat it as a hot-path root exactly like jax.shard_map.
    _write(tmp_path, "esac_tpu/parallel/sharded.py", """\
        from functools import partial

        import jax.numpy as jnp
        from esac_tpu.parallel.mesh import shard_map

        @partial(shard_map, mesh=None, in_specs=(), out_specs=())
        def local_step(A, b):
            return jnp.linalg.solve(A, b)
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R3"]
    assert "solve" in findings[0].text


def test_r3_vmap_callsite_roots_and_cross_module(tmp_path):
    _write(tmp_path, "esac_tpu/geometry/alg.py", """\
        import jax.numpy as jnp

        def invert(A):
            return jnp.linalg.inv(A)
        """)
    _write(tmp_path, "esac_tpu/ransac/driver.py", """\
        import jax
        from esac_tpu.geometry.alg import invert

        def run(As):
            return jax.vmap(lambda A: invert(A))(As)
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R3"]
    assert findings[0].path == "esac_tpu/geometry/alg.py"


# --------------------------------------------------------------------------
# R4: unpinned contractions in precision-pinned modules

def test_r4_trigger_and_near_miss(tmp_path):
    _write(tmp_path, "esac_tpu/geometry/rot.py", """\
        import jax.numpy as jnp

        def compose(a, b):
            return jnp.matmul(a, b)

        def compose_op(a, b):
            return a @ b
        """)
    _write(tmp_path, "esac_tpu/geometry/rot_ok.py", """\
        import jax
        import jax.numpy as jnp

        def compose(a, b):
            return jnp.matmul(a, b, precision=jax.lax.Precision.HIGHEST)
        """)
    _write(tmp_path, "esac_tpu/models/net.py", """\
        import jax.numpy as jnp

        def dense(a, b):
            return jnp.matmul(a, b)    # CNN-side module: not pinned scope
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R4", "R4"]
    assert all(f.path == "esac_tpu/geometry/rot.py" for f in findings)


# --------------------------------------------------------------------------
# R5: config dataclasses must be frozen

def test_r5_trigger_and_near_miss(tmp_path):
    _write(tmp_path, "esac_tpu/confs.py", """\
        import dataclasses
        from dataclasses import dataclass

        @dataclass
        class BadConfig:
            n: int = 1

        @dataclasses.dataclass(frozen=True)
        class GoodConfig:
            n: int = 1

        @dataclass
        class Frame:            # not a *Config: data record, no static-arg use
            n: int = 1
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R5"]
    assert "BadConfig" in findings[0].message


# --------------------------------------------------------------------------
# R6: force-CPU guard in ad-hoc scripts

def test_r6_trigger_and_near_misses(tmp_path):
    _write(tmp_path, "tools/bad_tool.py", """\
        import jax

        def main():
            print(jax.devices())
        """)
    _write(tmp_path, "tools/good_tool.py", """\
        import jax

        jax.config.update("jax_platforms", "cpu")

        def main():
            print(jax.devices())
        """)
    _write(tmp_path, "tools/stdlib_tool.py", """\
        import json

        def main():
            print(json.dumps({}))
        """)
    _write(tmp_path, "esac_tpu/library.py", """\
        import jax                 # library module: R6 is script-scope only
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R6"]
    assert findings[0].path == "tools/bad_tool.py"


def test_r6_esac_tpu_import_counts_as_jax_adjacent(tmp_path):
    _write(tmp_path, "experiments/probe.py", """\
        from esac_tpu.ransac import RansacConfig
        """)
    assert _rules(run_layer1(tmp_path)) == ["R6"]


# --------------------------------------------------------------------------
# R7: shell timeout/kill around python

def test_r7_trigger_and_near_miss(tmp_path):
    _write(tmp_path, "experiments/bad.sh", """\
        #!/bin/sh
        timeout 600 python train_esac.py --cpu
        kill $TRAINER_PID
        """)
    _write(tmp_path, "experiments/good.sh", """\
        #!/bin/sh
        # never kill the trainer (prose mention is fine)
        while kill -0 $TRAINER_PID 2>/dev/null; do sleep 5; done
        setsid nohup python tools/tpu_probe.py > probe.log 2>&1 &
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R7", "R7"]
    assert all(f.path == "experiments/bad.sh" for f in findings)


# --------------------------------------------------------------------------
# suppressions

def test_inline_suppression_silences_finding(tmp_path):
    _write(tmp_path, "esac_tpu/geometry/sup.py", """\
        import jax.numpy as jnp

        def n(v):
            return jnp.linalg.norm(v)  # graft-lint: disable=R2(fixture reason)
        """)
    assert run_layer1(tmp_path) == []


def test_file_level_suppression(tmp_path):
    _write(tmp_path, "tools/chip_tool.py", """\
        # graft-lint: disable-file=R6(sanctioned chip toucher - fixture)
        import jax
        """)
    assert run_layer1(tmp_path) == []


def test_shell_suppression(tmp_path):
    _write(tmp_path, "experiments/sup.sh", """\
        #!/bin/sh
        kill $PID  # graft-lint: disable=R7(fixture: pid is a sleep, not jax)
        """)
    assert run_layer1(tmp_path) == []


def test_suppression_parser():
    per_line, per_file = parse_suppressions(
        "x = 1  # graft-lint: disable=R1,R4(two rules one line)\n"
        "# graft-lint: disable-file=R6(whole file)\n"
    )
    assert per_line == {1: {"R1", "R4"}}
    assert per_file == {"R6"}


def test_multiline_reason_does_not_widen_suppression():
    # A reason that wraps to the next comment line (unclosed paren) ends the
    # rule list: rule ids mentioned in the prose must not get suppressed.
    per_line, per_file = parse_suppressions(
        "# graft-lint: disable-file=R6(guards R2 and\n"
        "# R3 style issues elsewhere)\n"
    )
    assert per_file == {"R6"}
    assert per_line == {}


# --------------------------------------------------------------------------
# baseline: grandfathering + expiry

def _one_r2_finding(tmp_path):
    _write(tmp_path, "esac_tpu/geometry/base.py", """\
        import jax.numpy as jnp

        def n(v):
            return jnp.linalg.norm(v)
        """)
    findings = run_layer1(tmp_path)
    assert _rules(findings) == ["R2"]
    return findings


def test_baseline_masks_matching_finding(tmp_path):
    findings = _one_r2_finding(tmp_path)
    b = Baseline.from_findings(findings)
    remaining, stale = b.apply(findings)
    assert remaining == [] and stale == []


def test_baseline_is_line_number_independent(tmp_path):
    findings = _one_r2_finding(tmp_path)
    f = findings[0]
    b = Baseline([BaselineEntry(rule=f.rule, path=f.path, text=f.text)])
    # Same offending line, shifted by edits above it: still masked.
    shifted = [type(f)(f.rule, f.path, f.line + 10, f.text, f.message)]
    remaining, stale = b.apply(shifted)
    assert remaining == [] and stale == []


def test_baseline_expiry_resurfaces_finding(tmp_path):
    findings = _one_r2_finding(tmp_path)
    f = findings[0]
    expired = BaselineEntry(rule=f.rule, path=f.path, text=f.text,
                            expires="2026-01-01")
    b = Baseline([expired])
    remaining, stale = b.apply(findings,
                               today=datetime.date(2026, 6, 1))
    assert remaining == findings          # mask no longer applies
    assert stale == [expired]             # and the entry is reported stale
    # Before expiry the same entry still masks.
    remaining, stale = b.apply(findings,
                               today=datetime.date(2025, 12, 1))
    assert remaining == [] and stale == []


def test_baseline_unused_entry_is_stale(tmp_path):
    b = Baseline([BaselineEntry(rule="R2", path="gone.py", text="x = 1")])
    remaining, stale = b.apply([])
    assert remaining == [] and len(stale) == 1


def test_baseline_roundtrip(tmp_path):
    findings = _one_r2_finding(tmp_path)
    path = tmp_path / "baseline.json"
    Baseline.from_findings(findings).write(path)
    loaded = Baseline.load(path)
    remaining, _ = loaded.apply(findings)
    assert remaining == []
    assert json.loads(path.read_text())["entries"]


# --------------------------------------------------------------------------
# CLI exit codes (driver contract: 0 clean, 1 findings, 2 internal error)

def test_cli_exit_1_on_seeded_violations_of_every_rule(tmp_path, capsys):
    _write(tmp_path, "esac_tpu/r1.py",
           "import jax.numpy as jnp\nX = jnp.zeros(3)\n")
    _write(tmp_path, "esac_tpu/geometry/r2.py",
           "import jax.numpy as jnp\n\ndef n(v):\n"
           "    return jnp.linalg.norm(v)\n")
    _write(tmp_path, "esac_tpu/ransac/r3.py",
           "import jax\nimport jax.numpy as jnp\n\n@jax.jit\ndef h(A, b):\n"
           "    return jnp.linalg.solve(A, b)\n")
    _write(tmp_path, "esac_tpu/geometry/r4.py",
           "import jax.numpy as jnp\n\ndef m(a, b):\n"
           "    return jnp.matmul(a, b)\n")
    _write(tmp_path, "esac_tpu/r5.py",
           "from dataclasses import dataclass\n\n@dataclass\n"
           "class LintFixtureConfig:\n    n: int = 1\n")
    _write(tmp_path, "tools/r6.py", "import jax\n")
    _write(tmp_path, "experiments/r7.sh", "timeout 5 python x.py\n")
    rc = lint_main(["--root", str(tmp_path), "--no-jaxpr"])
    out = capsys.readouterr().out
    assert rc == 1
    for rule in ("R1", "R2", "R3", "R4", "R5", "R6", "R7"):
        assert f" {rule} " in out, f"{rule} missing from CLI output:\n{out}"


def test_cli_exit_0_on_clean_tree(tmp_path, capsys):
    _write(tmp_path, "esac_tpu/ok.py", "import numpy as np\nX = np.zeros(3)\n")
    assert lint_main(["--root", str(tmp_path), "--no-jaxpr"]) == 0


def test_cli_exit_2_on_malformed_baseline(tmp_path, capsys):
    # Driver contract: a broken baseline file is an internal error (2),
    # never to be misread as findings (1).
    _write(tmp_path, "esac_tpu/ok.py", "import numpy as np\n")
    bad = tmp_path / "baseline.json"
    bad.write_text('{"entries": [{"rule": "R2", "bogus": 1}]}\n')
    assert lint_main(["--root", str(tmp_path), "--no-jaxpr",
                      "--baseline", str(bad)]) == 2


def test_changed_mode_audits_on_utils_edits():
    # utils/precision.py and utils/num.py carry the invariants the jaxpr
    # audit enforces; a --changed run touching them must include layer 2.
    from esac_tpu.lint.cli import _audit_needed

    assert _audit_needed(["esac_tpu/utils/precision.py"])
    assert _audit_needed(["esac_tpu/utils/num.py"])
    assert not _audit_needed(["tools/eval_agreement.py", "LINT.md"])


def test_cli_write_baseline_then_clean(tmp_path, capsys):
    _write(tmp_path, "esac_tpu/geometry/r2.py",
           "import jax.numpy as jnp\n\ndef n(v):\n"
           "    return jnp.linalg.norm(v)\n")
    base = tmp_path / "baseline.json"
    assert lint_main(["--root", str(tmp_path), "--no-jaxpr",
                      "--baseline", str(base), "--write-baseline"]) == 0
    assert lint_main(["--root", str(tmp_path), "--no-jaxpr",
                      "--baseline", str(base)]) == 0


def test_cli_write_baseline_refuses_scoped_runs(tmp_path, capsys):
    # A scoped --write-baseline would replace the whole file with the
    # slice's findings, deleting every entry for unscanned files.
    rel = _write(tmp_path, "esac_tpu/geometry/r2.py",
                 "import jax.numpy as jnp\n\ndef n(v):\n"
                 "    return jnp.linalg.norm(v)\n")
    base = tmp_path / "baseline.json"
    assert lint_main(["--root", str(tmp_path), "--no-jaxpr",
                      "--baseline", str(base), "--write-baseline", rel]) == 2
    assert not base.exists()


# --------------------------------------------------------------------------
# layer 2: jaxpr auditor

def test_audit_flags_unpinned_dot_in_pinned_graph():
    import jax
    import jax.numpy as jnp

    from esac_tpu.lint.jaxpr_audit import audit_jaxpr

    a = jnp.zeros((3, 3))
    closed = jax.make_jaxpr(lambda x, y: jnp.matmul(x, y))(a, a)
    findings = audit_jaxpr("fixture", closed, pinned=True)
    assert [f.rule for f in findings] == ["J3"]
    # The identical trace in an unpinned graph is fine.
    assert audit_jaxpr("fixture", closed, pinned=False) == []


def test_audit_accepts_hmm():
    import jax
    import jax.numpy as jnp

    from esac_tpu.lint.jaxpr_audit import audit_jaxpr
    from esac_tpu.utils.precision import hmm

    a = jnp.zeros((3, 3))
    closed = jax.make_jaxpr(hmm)(a, a)
    assert audit_jaxpr("fixture", closed, pinned=True) == []


def test_audit_flags_while_loop():
    import jax

    from esac_tpu.lint.jaxpr_audit import audit_jaxpr

    def dynamic_trip(x):
        return jax.lax.while_loop(
            lambda v: v[0] < 8, lambda v: (v[0] + 1, v[1] * 0.5), (0, x)
        )[1]

    closed = jax.make_jaxpr(dynamic_trip)(1.0)
    findings = audit_jaxpr("fixture", closed, pinned=False)
    assert any(f.rule == "J1" and f.text == "while" for f in findings)


def test_audit_recurses_into_scan_and_jit():
    import jax
    import jax.numpy as jnp

    from esac_tpu.lint.jaxpr_audit import audit_jaxpr

    @jax.jit
    def scanned(x):
        def body(carry, _):
            return jnp.matmul(carry, carry), None

        out, _ = jax.lax.scan(body, x, None, length=3)
        return out

    closed = jax.make_jaxpr(scanned)(jnp.eye(3))
    findings = audit_jaxpr("fixture", closed, pinned=True)
    assert [f.rule for f in findings] == ["J3"]  # found inside scan-in-pjit


def test_registered_entry_points_audit_clean():
    """The acceptance gate: every registry entry traces on CPU with zero
    disallowed primitives, static shapes, and pinned call graphs at
    HIGHEST/f32 — the jaxpr-level form of the CLAUDE.md conventions."""
    from esac_tpu.lint.jaxpr_audit import run_audit

    findings = run_audit()
    assert findings == [], "\n".join(f.format() for f in findings)


# --------------------------------------------------------------------------
# the tier-1 gate: committed tree clean modulo baseline

def test_committed_tree_is_clean_modulo_baseline():
    findings = run_layer1(REPO)
    baseline = Baseline.load(REPO / "lint_baseline.json")
    remaining, _ = baseline.apply(findings)
    assert remaining == [], "\n".join(f.format() for f in remaining)


def test_committed_baseline_has_no_stale_entries():
    findings = run_layer1(REPO)
    baseline = Baseline.load(REPO / "lint_baseline.json")
    _, stale = baseline.apply(findings)
    assert stale == [], f"stale baseline entries: {stale}"
