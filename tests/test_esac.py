"""Multi-expert ESAC tests: routing, selection, dense & sampled estimators."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from esac_tpu.data import CAMERA_F, make_correspondence_frame
from esac_tpu.geometry import pose_errors, rodrigues
from esac_tpu.ransac import RansacConfig, esac_infer, esac_train_loss

F = jnp.float32(CAMERA_F / 4.0)
C = jnp.array([80.0, 60.0])
FRAME_KW = dict(height=120, width=160, f=CAMERA_F / 4.0, c=(80.0, 60.0))
CFG = RansacConfig(n_hyps=32, refine_iters=4, train_refine_iters=1)
M = 4


def make_multi_expert_frame(key, correct_expert=1, noise=0.01):
    """One frame where only `correct_expert`'s coord map is right; the other
    experts output heavily corrupted maps (as experts of OTHER scenes would).
    """
    frame = make_correspondence_frame(key, noise=noise, **FRAME_KW)
    n = frame["coords"].shape[0]
    maps = []
    for m in range(M):
        if m == correct_expert:
            maps.append(frame["coords"])
        else:
            k = jax.random.fold_in(key, 100 + m)
            maps.append(jax.random.uniform(k, (n, 3), minval=0.0, maxval=5.0))
    return jnp.stack(maps), frame


def test_esac_infer_picks_correct_expert():
    coords_all, frame = make_multi_expert_frame(jax.random.key(0), correct_expert=2)
    logits = jnp.zeros(M)  # uninformative gate: consensus must decide
    out = esac_infer(jax.random.key(1), logits, coords_all, frame["pixels"], F, C, CFG)
    assert int(out["expert"]) == 2
    r_err, t_err = pose_errors(
        rodrigues(out["rvec"]), out["tvec"], rodrigues(frame["rvec"]), frame["tvec"]
    )
    assert r_err < 5.0 and t_err < 0.05


@pytest.mark.parametrize("mode", [
    "dense",
    # Tier-1 budget (TODO item 9, ISSUE 17): the sampled leg is ~19s; the
    # REINFORCE estimator's gradient keeps tier-1 coverage via
    # test_sampled_reinforce_gating_gradient_direction below.
    pytest.param("sampled", marks=pytest.mark.slow),
])
def test_esac_train_loss_finite_and_gradient_flows(mode):
    coords_all, frame = make_multi_expert_frame(jax.random.key(2))
    logits = jnp.array([0.1, 1.0, -0.3, 0.2])
    R_gt, t_gt = rodrigues(frame["rvec"]), frame["tvec"]

    def loss_fn(lg, ca):
        loss, _ = esac_train_loss(
            jax.random.key(3), lg, ca, frame["pixels"], F, C, R_gt, t_gt, CFG, mode
        )
        return loss

    loss = loss_fn(logits, coords_all)
    assert jnp.isfinite(loss)
    g_logits, g_coords = jax.grad(loss_fn, argnums=(0, 1))(logits, coords_all)
    assert jnp.all(jnp.isfinite(g_logits)) and jnp.any(g_logits != 0)
    assert jnp.all(jnp.isfinite(g_coords)) and jnp.any(g_coords != 0)


def test_dense_gating_gradient_prefers_correct_expert():
    """Pushing gating toward the correct expert must lower the dense loss, so
    the gradient at uniform gating must point toward that expert."""
    coords_all, frame = make_multi_expert_frame(jax.random.key(4), correct_expert=1)
    R_gt, t_gt = rodrigues(frame["rvec"]), frame["tvec"]

    def loss_fn(lg):
        loss, _ = esac_train_loss(
            jax.random.key(5), lg, coords_all, frame["pixels"], F, C, R_gt, t_gt,
            CFG, "dense",
        )
        return loss

    g = jax.grad(loss_fn)(jnp.zeros(M))
    # Negative gradient = increasing that logit lowers the loss.
    assert int(jnp.argmin(g)) == 1, g


def test_sampled_reinforce_gating_gradient_direction():
    """Averaged over draws, the REINFORCE gating gradient must also favor the
    correct expert (statistical check, SURVEY.md hard part #5)."""
    coords_all, frame = make_multi_expert_frame(jax.random.key(6), correct_expert=3)
    R_gt, t_gt = rodrigues(frame["rvec"]), frame["tvec"]

    def loss_fn(lg, key):
        loss, _ = esac_train_loss(
            key, lg, coords_all, frame["pixels"], F, C, R_gt, t_gt, CFG, "sampled"
        )
        return loss

    grads = [
        jax.grad(loss_fn)(jnp.zeros(M), jax.random.key(50 + i)) for i in range(6)
    ]
    g = jnp.mean(jnp.stack(grads), axis=0)
    assert int(jnp.argmin(g)) == 3, g


def test_gating_probs_reported():
    coords_all, frame = make_multi_expert_frame(jax.random.key(8))
    logits = jnp.array([3.0, 0.0, 0.0, 0.0])
    out = esac_infer(jax.random.key(9), logits, coords_all, frame["pixels"], F, C, CFG)
    assert out["gating_probs"].shape == (M,)
    assert float(out["gating_probs"][0]) > 0.8


def test_topk_pruned_inference():
    """Top-k gating pruning: correct result when the gate ranks the right
    expert in the top k; the winner index maps back to the full ensemble."""
    coords_all, frame = make_multi_expert_frame(jax.random.key(20), correct_expert=3)
    from esac_tpu.ransac import esac_infer_topk

    logits = jnp.array([0.0, 0.5, 0.2, 2.0])  # gate favors the right expert
    out = esac_infer_topk(
        jax.random.key(21), logits, coords_all, frame["pixels"], F, C, CFG, k=2
    )
    assert int(out["expert"]) == 3
    assert out["experts_evaluated"].shape == (2,)
    r_err, t_err = pose_errors(
        rodrigues(out["rvec"]), out["tvec"], rodrigues(frame["rvec"]), frame["tvec"]
    )
    assert r_err < 5.0 and t_err < 0.05


def test_topk_miss_behaves_like_reference():
    """If the gate excludes the true expert from top-k, the frame fails —
    the reference's drawn-subset failure mode, reported honestly."""
    coords_all, frame = make_multi_expert_frame(jax.random.key(22), correct_expert=0)
    from esac_tpu.ransac import esac_infer_topk

    logits = jnp.array([-5.0, 2.0, 1.0, 0.5])  # gate wrongly buries expert 0
    out = esac_infer_topk(
        jax.random.key(23), logits, coords_all, frame["pixels"], F, C, CFG, k=2
    )
    assert int(out["expert"]) != 0
    assert float(out["inlier_frac"]) < 0.3  # low consensus exposes the miss


def test_esac_infer_with_subsampled_scoring():
    coords_all, frame = make_multi_expert_frame(jax.random.key(30), correct_expert=1)
    n = frame["coords"].shape[0]
    cfg = RansacConfig(n_hyps=32, refine_iters=4, score_cells=n // 4)
    out = esac_infer(jax.random.key(31), jnp.zeros(M), coords_all, frame["pixels"], F, C, cfg)
    assert int(out["expert"]) == 1
    r_err, t_err = pose_errors(
        rodrigues(out["rvec"]), out["tvec"], rodrigues(frame["rvec"]), frame["tvec"]
    )
    assert r_err < 5.0 and t_err < 0.05


def test_config3_shape_twelve_experts_1024_hyps():
    """BASELINE config #3 structure: 12 experts, 1024 hypotheses vmap'd —
    must compile and localize on the test mesh (reduced cells for CPU CI)."""
    frame = make_correspondence_frame(jax.random.key(40), noise=0.01, **FRAME_KW)
    n = frame["coords"].shape[0]
    correct = 7
    maps = jnp.stack([
        frame["coords"] if m == correct
        else jax.random.uniform(jax.random.fold_in(jax.random.key(41), m), (n, 3), maxval=5.0)
        for m in range(12)
    ])
    cfg = RansacConfig(n_hyps=1024, refine_iters=4, score_cells=n // 2)
    out = esac_infer(jax.random.key(42), jnp.zeros(12), maps, frame["pixels"], F, C, cfg)
    assert out["scores"].shape == (12, 1024)
    assert int(out["expert"]) == correct
    r_err, t_err = pose_errors(
        rodrigues(out["rvec"]), out["tvec"], rodrigues(frame["rvec"]), frame["tvec"]
    )
    assert r_err < 5.0 and t_err < 0.05


def test_topk_gating_probs_full_distribution():
    """ADVICE r1: esac_infer_topk must report the full M-way softmax like
    esac_infer, not a renormalization over the k pruned experts."""
    coords_all, frame = make_multi_expert_frame(jax.random.key(9), correct_expert=1)
    logits = jnp.array([2.0, 1.0, 0.0, -1.0])
    from esac_tpu.ransac import esac_infer_topk

    out = esac_infer_topk(
        jax.random.key(1), logits, coords_all, frame["pixels"], F, C, CFG, k=2
    )
    np.testing.assert_allclose(
        np.asarray(out["gating_probs"]), np.asarray(jax.nn.softmax(logits)),
        rtol=1e-6,
    )
    assert out["scores"].shape == (2, CFG.n_hyps)
