"""Worker process for the multi-process (DCN) mesh test.

Usage: python tests/mp_worker.py <process_id> <coordinator_port>

Two of these run side by side (tests/test_multiprocess.py), each holding 4
CPU devices, and bootstrap a 2-process jax.distributed cluster through
``esac_tpu.parallel.initialize_multihost`` — the claim under test is that
the mesh/collective code in ``esac_tpu.parallel`` is host-count agnostic
(PARALLELISM.md): the ``data`` axis lands across processes (the DCN axis on
real multi-slice hardware) and the ``expert`` axis within a process (ICI),
and one sharded ESAC loss+grad step runs to the same finite value on every
process with no code path caring how many hosts back the mesh.

Prints ``MP_OK loss=<v> gnorm=<v>`` on success; any mismatch/failure raises.
"""

from __future__ import annotations

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> None:
    pid, port = int(sys.argv[1]), int(sys.argv[2])
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 4)

    from esac_tpu.parallel import initialize_multihost

    info = initialize_multihost(
        coordinator_address=f"localhost:{port}", num_processes=2, process_id=pid
    )
    assert info["process_count"] == 2, info
    assert info["local_devices"] == 4 and info["global_devices"] == 8, info

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from esac_tpu.models import ExpertNet, GatingNet
    from esac_tpu.parallel import make_mesh
    from esac_tpu.parallel.train_sharded import make_sharded_esac_loss
    from esac_tpu.data import output_pixel_grid
    from esac_tpu.ransac import RansacConfig

    H = W = 32
    M, batch = 4, 2
    mesh = make_mesh(n_data=2, n_expert=4)

    expert = ExpertNet(scene_center=(0.0, 0.0, 2.0), stem_channels=(4, 8, 8),
                       head_channels=8, head_depth=1,
                       compute_dtype=jnp.float32)
    gating = GatingNet(num_experts=M, channels=(4, 8),
                       compute_dtype=jnp.float32)
    img = jnp.zeros((1, H, W, 3))
    # Same seeds in both processes -> identical host-side params.
    e_params = jax.vmap(lambda k: expert.init(k, img))(
        jax.random.split(jax.random.key(0), M)
    )
    g_params = gating.init(jax.random.key(1), img)

    def globalize(tree, spec):
        """Host arrays -> global sharded jax.Arrays on the 2-process mesh."""

        def one(x):
            x = np.asarray(x)
            sh = NamedSharding(mesh, spec)
            return jax.make_array_from_callback(
                x.shape, sh, lambda idx: x[idx]
            )

        return jax.tree.map(one, tree)

    e_params = globalize(e_params, P("expert"))
    g_params = globalize(g_params, P())

    # Batch data: process-local halves of a globally consistent batch.
    rng = np.random.default_rng(7)
    images_h = rng.uniform(size=(batch, H, W, 3)).astype(np.float32)
    R_h = np.tile(np.eye(3, dtype=np.float32), (batch, 1, 1))
    t_h = np.tile(np.array([0.0, 0.0, 2.0], np.float32), (batch, 1))
    images = globalize(images_h, P("data"))
    R_gts = globalize(R_h, P("data", None, None))
    t_gts = globalize(t_h, P("data"))

    pixels = output_pixel_grid(H, W, 8)
    cfg = RansacConfig(n_hyps=8, train_refine_iters=1, polish_iters=1)
    loss_fn = make_sharded_esac_loss(
        mesh, expert, gating, e_params, g_params, pixels,
        jnp.float32(40.0), jnp.asarray([W / 2.0, H / 2.0]), cfg,
    )

    grad_fn = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1)))
    loss, grads = grad_fn(e_params, g_params, images, R_gts, t_gts,
                          jax.random.key(3))
    loss = float(loss)
    gnorm = float(
        jnp.sqrt(sum(jnp.sum(g * g) for g in jax.tree.leaves(grads)))
    )
    assert np.isfinite(loss) and np.isfinite(gnorm) and gnorm > 0.0
    print(f"MP_OK loss={loss:.6f} gnorm={gnorm:.6f}", flush=True)


if __name__ == "__main__":
    main()
