"""Observability-layer tests (ISSUE 10, DESIGN.md §14).

The load-bearing claims:

- the streaming-quantile histogram that replaced ``latency_quantiles``'s
  sort-the-whole-deque reports p50/p99 within a PINNED tolerance of the
  exact nearest-rank values, at fixed memory, over a recent window;
- every traced request's span durations sum (math.fsum) to its measured
  end-to-end latency, and tracing off leaves the request path unstamped;
- the four legacy snapshot surfaces (``slo_totals``,
  ``dispatch_totals``, ``SceneRegistry.health``,
  ``DeviceWeightCache.stats``) keep their pre-refactor shapes while
  being views over / collectors of the unified obs registry;
- ``obs.snapshot()`` is ``json.dumps``-able, stays CONSISTENT
  mid-traffic (outcome classes + pending sum to offered in every
  concurrent read) and never blocks admission — even while a dispatch is
  wedged.
"""

import json
import math
import threading
import time

import numpy as np
import pytest

from esac_tpu.obs import (
    OBS_SCHEMA,
    MetricsRegistry,
    SpanChain,
    StreamingHistogram,
    jsonable,
    render_prometheus,
)
from esac_tpu.ransac import RansacConfig
from esac_tpu.serve import (
    FaultInjector,
    MicroBatchDispatcher,
    SLOPolicy,
    run_open_loop,
    uniform_arrivals,
)

CFG = RansacConfig(n_hyps=8, frame_buckets=(1, 4), serve_max_wait_ms=1.0)

# The pinned histogram tolerance: bucket growth 1.07 bounds the relative
# quantile error at sqrt(1.07)-1 ~= 3.4%; 5% leaves margin for the
# nearest-rank discretization at small sample counts.
QUANTILE_RTOL = 0.05


def _echo(tree, scene=None, route_k=None):
    return {"echo": tree["x"]}


def _frame(v=0.0):
    return {"x": np.full(2, v, np.float32)}


def _exact_rank(sorted_xs, q):
    return sorted_xs[min(len(sorted_xs) - 1, round(q * (len(sorted_xs) - 1)))]


# ---------------- streaming histogram (satellite 1) ----------------

def test_histogram_quantiles_within_pinned_tolerance():
    import random

    rng = random.Random(0)
    h = StreamingHistogram(window=5000)
    xs = [rng.lognormvariate(-5.0, 1.0) for _ in range(20_000)]
    for x in xs:
        h.observe(x)
    recent = sorted(xs[-5000:])
    for q in (0.5, 0.9, 0.99):
        exact = _exact_rank(recent, q)
        est = h.quantile(q)
        assert abs(est - exact) / exact <= QUANTILE_RTOL, (q, exact, est)


def test_histogram_window_tracks_recent_distribution():
    import random

    rng = random.Random(1)
    h = StreamingHistogram(window=2000)
    for _ in range(10_000):
        h.observe(rng.lognormvariate(-6.0, 0.3))  # ~2.5ms scale
    for _ in range(4000):  # > window: the old regime must rotate out
        h.observe(rng.lognormvariate(-3.0, 0.3))  # ~50ms scale
    p50 = h.quantile(0.5)
    assert 0.02 < p50 < 0.12, p50  # the NEW scale, not the old one


def test_histogram_fixed_memory_and_edges():
    h = StreamingHistogram(window=100, epochs=4)
    assert math.isnan(h.quantile(0.5))
    for i in range(100_000):
        h.observe(1e-3 * (1 + (i % 7)))
    # memory: at most `epochs` bucket arrays, however many samples landed
    assert len(h._counts) <= 4
    # non-finite / non-positive samples clamp, never raise or corrupt
    h.observe(float("nan"))
    h.observe(-1.0)
    h.observe(float("inf"))
    assert h.quantile(0.5) > 0
    s = h.summary()
    assert s["count"] > 0 and s["p50"] == h.quantile(0.5)
    # single-sample histogram reports the sample exactly (min/max clamp)
    h2 = StreamingHistogram()
    h2.observe(0.25)
    assert h2.quantile(0.5) == pytest.approx(0.25)


# ---------------- registry / instruments / export ----------------

def test_registry_instruments_idempotent_and_kind_checked():
    r = MetricsRegistry()
    c = r.counter("x_total", "help")
    assert r.counter("x_total") is c
    with pytest.raises(ValueError):
        r.gauge("x_total")
    c.inc(2, lane="a")
    c.inc(lane="b")
    assert c.total() == 3
    assert c.get(lane="a") == 2
    c.rebase(7, lane="a")
    assert c.get(lane="a") == 7
    c.reset()
    assert c.total() == 0
    g = r.gauge("g")
    g.set(1.5, k="v")
    assert g.get(k="v") == 1.5
    assert math.isnan(g.get(k="other"))


def test_registry_adopting_shared_instruments():
    a, b = MetricsRegistry(), MetricsRegistry()
    c = a.counter("shared_total")
    b.register(c)
    c.inc(5)
    assert b.get("shared_total").total() == 5
    b.register(c)  # re-adopt: no-op
    with pytest.raises(ValueError):
        b.register(MetricsRegistry().counter("shared_total"))


def test_snapshot_json_dumpable_with_hostile_collectors():
    import collections

    r = MetricsRegistry()
    r.counter("c_total").inc(scene=None, route_k=2)
    r.histogram("h_seconds", window=10).observe(0.01, stage="device")
    r.register_collector("tuple_keys", lambda: {("s0", None): 1})
    r.register_collector("numpyish", lambda: {"v": np.float32(1.5),
                                              "n": np.int64(3)})
    r.register_collector("dequeish",
                         lambda: collections.deque([1, 2], maxlen=4))
    r.register_collector("sick", lambda: 1 / 0)
    snap = r.snapshot()
    text = json.dumps(snap)  # the contract: NEVER raises
    assert snap["obs_schema"] == OBS_SCHEMA
    assert snap["collectors"]["tuple_keys"] == {"('s0', None)": 1}
    assert snap["collectors"]["numpyish"] == {"v": 1.5, "n": 3}
    assert snap["collectors"]["dequeish"] == [1, 2]
    assert "ZeroDivisionError" in snap["collectors"]["sick"]["error"]
    assert "c_total" in text and "h_seconds" in text


def test_render_prometheus_format():
    r = MetricsRegistry()
    r.counter("req_total", "requests").inc(3, scene="s0")
    r.histogram("lat_seconds", window=10).observe(0.25)
    page = r.render_prometheus()
    assert "# TYPE req_total counter" in page
    assert 'req_total{scene="s0"} 3.0' in page
    assert "# TYPE lat_seconds summary" in page
    assert 'lat_seconds{quantile="0.5"}' in page
    assert "lat_seconds_count" in page


def test_jsonable_stringifies_odd_keys_and_leaves():
    out = jsonable({(1, None): {np.float64(2.0), "x"}, "a": (1, 2)})
    json.dumps(out)
    assert out["(1, None)"] is not None and out["a"] == [1, 2]


# ---------------- span chains ----------------

def test_span_chain_durations_telescope():
    ch = SpanChain("admitted", 10.0)
    ch.stamp("coalesced", 10.5)
    ch.stamp("staged", 10.6)
    ch.stamp("staged", 10.9)  # retry re-stamp: aggregation must survive
    ch.stamp("served", 11.25)
    d = ch.durations()
    assert d["staged"] == pytest.approx(0.4)
    assert math.fsum(d.values()) == pytest.approx(ch.total())
    assert ch.residual() < 1e-12
    assert ch.total() == pytest.approx(1.25)


def test_traced_dispatcher_spans_sum_to_measured_latency():
    disp = MicroBatchDispatcher(_echo, CFG, trace=True)
    try:
        reqs = [disp.submit(_frame(i), scene=f"s{i % 2}") for i in range(8)]
        for r in reqs:
            r.get(60.0)
        for r in reqs:
            stages = [s for s, _ in r.spans.stamps]
            assert stages[0] == "admitted" and stages[-1] == "served"
            assert {"coalesced", "staged", "dispatched", "device",
                    "sliced"} <= set(stages)
            # the acceptance pin: per-stage durations sum EXACTLY (fsum)
            # to the measured end-to-end latency
            resid = abs(math.fsum(r.spans.durations().values())
                        - (r.t_done - r.t_submit))
            assert resid < 1e-9, (stages, resid)
        stage_hist = disp.obs.get("serve_stage_seconds")
        for stage in ("coalesced", "staged", "dispatched", "device",
                      "sliced", "served"):
            assert stage_hist.count(stage=stage) == len(reqs), stage
    finally:
        disp.close()


def test_tracing_off_leaves_requests_unstamped_but_metrics_on():
    disp = MicroBatchDispatcher(_echo, CFG)
    try:
        req = disp.submit(_frame(1.0))
        req.get(60.0)
        assert req.spans is None
        assert disp.obs.get("serve_stage_seconds").count() == 0
        assert disp.obs.get("serve_offered_total").total() == 1
        assert disp.obs.get("serve_outcomes_total").get(outcome="served") == 1
    finally:
        disp.close()


# ---------------- legacy snapshot surfaces: exact-compat pins ----------

def test_slo_and_dispatch_views_match_legacy_attributes():
    disp = MicroBatchDispatcher(_echo, CFG, start_worker=False)
    for i in range(60):
        disp.infer_one(_frame(i), scene=f"s{i % 3}",
                       route_k=(i % 2) or None)
    t = disp.slo_totals()
    assert set(t) == {"offered", "served", "shed", "expired", "degraded",
                      "failed", "pending"}
    assert all(isinstance(v, int) for v in t.values())
    # the view and the legacy attributes tell ONE story
    assert t["offered"] == disp.offered == 60
    assert t["served"] == disp.outcome_counts["served"] == 60
    totals = disp.dispatch_totals()
    assert totals == dict(disp.dispatch_counts)
    assert all(isinstance(k, tuple) and len(k) == 2 for k in totals)
    # satellite 1: the histogram-backed quantiles stay within the pinned
    # tolerance of exact nearest-rank over the SAME window
    lat = sorted(disp.latencies_s)
    q = disp.latency_quantiles()
    assert set(q) == {0.5, 0.99}
    for p, est in q.items():
        exact = _exact_rank(lat, p)
        assert abs(est - exact) / exact <= QUANTILE_RTOL, (p, exact, est)


def test_reset_stats_rebases_obs_views_too():
    disp = MicroBatchDispatcher(_echo, CFG, start_worker=False)
    for i in range(5):
        disp.infer_one(_frame(i), scene="s")
    disp.reset_stats()
    t = disp.slo_totals()
    assert t["offered"] == 0 and t["served"] == 0 and t["pending"] == 0
    assert disp.dispatch_totals() == {}
    assert math.isnan(disp.latency_quantiles()[0.5])
    disp.infer_one(_frame(9), scene="s")
    t = disp.slo_totals()
    assert t["offered"] == t["served"] == 1


def test_cache_stats_and_registry_health_shapes_pinned():
    from esac_tpu.registry import (
        DeviceWeightCache, SceneManifest, SceneRegistry,
    )

    cache = DeviceWeightCache(lambda e: {})
    assert set(cache.stats()) == {
        "hits", "misses", "evictions", "resident", "bytes_in_use",
        "budget_bytes", "load_failures", "loads_in_flight",
        # Tier hierarchy classes (ISSUE 13, DESIGN.md §17): present —
        # zero-valued — on tierless caches too, so monitors see one
        # schema fleet-wide.
        "host_hits", "disk_loads", "demotions",
    }
    reg = SceneRegistry(SceneManifest())
    h = reg.health()
    assert set(h) == {"scenes", "canaries", "events"}
    assert h["scenes"] == {} and h["canaries"] == {} and h["events"] == []
    json.dumps(h)


def test_scene_registry_binds_into_dispatcher_obs():
    from esac_tpu.registry import SceneManifest, SceneRegistry

    reg = SceneRegistry(SceneManifest())
    disp = reg.dispatcher(CFG, start_worker=False)
    snap = disp.obs.snapshot()
    assert {"serve_slo_totals", "serve_dispatch_totals",
            "serve_quarantined_lanes", "scene_health",
            "weight_cache"} <= set(snap["collectors"])
    # shared instrument OBJECTS, not copies: one fleet truth
    assert disp.obs.get("registry_health_events_total") \
        is reg.obs.get("registry_health_events_total")
    assert snap["collectors"]["scene_health"]["scenes"] == {}
    assert snap["collectors"]["weight_cache"]["resident"] == 0
    json.dumps(snap)
    # a second dispatcher over the same registry adopts the same
    # instruments without error, but keeps PRIVATE serve accounting
    disp2 = reg.dispatcher(CFG, start_worker=False)
    assert disp2.obs is not disp.obs
    assert disp2.obs.get("registry_health_events_total") \
        is reg.obs.get("registry_health_events_total")


def test_fleet_snapshot_per_replica_merge_shape_pinned():
    """ISSUE 14: a FleetRouter's ``obs.snapshot()`` carries the
    per-replica-labelled fleet merge — every replica's serve accounting
    under its name, the affinity table, the route counts and the fleet
    accounting — json-dumpable, shapes pinned (the driver/monitor
    contract, like the cache/health shapes above)."""
    import numpy as np

    from esac_tpu.fleet import FleetPolicy, FleetRouter, Replica

    def echo(tree, scene=None, route_k=None):
        return {"echo": tree["x"]}

    reps = [
        Replica(f"r{i}", MicroBatchDispatcher(echo, CFG, slo=SLOPolicy()))
        for i in range(2)
    ]
    router = FleetRouter(reps, FleetPolicy(poll_ms=2.0))
    try:
        for i in range(4):
            router.infer_one({"x": np.full(2, float(i), np.float32)},
                             scene=f"s{i % 2}", deadline_ms=5_000)
        snap = router.obs.snapshot()
        json.dumps(snap)
        assert "fleet" in snap["collectors"]
        fleet = snap["collectors"]["fleet"]
        assert set(fleet) == {"replicas", "scene_homes", "route_counts",
                              "accounting"}
        assert set(fleet["replicas"]) == {"r0", "r1"}
        for block in fleet["replicas"].values():
            assert set(block) == {"slo", "quarantined", "inflight"}
            assert set(block["slo"]) == {"offered", "served", "shed",
                                         "expired", "degraded", "failed",
                                         "pending"}
        acc = fleet["accounting"]
        assert set(acc) == {"offered", "served", "shed", "expired",
                            "degraded", "failed", "pending"}
        assert (acc["served"] + acc["shed"] + acc["expired"]
                + acc["degraded"] + acc["failed"] + acc["pending"]
                == acc["offered"] == 4)
        # The fleet instruments ride the same registry.
        assert {"fleet_offered_total", "fleet_outcomes_total",
                "fleet_routes_total", "fleet_failovers_total",
                "fleet_events_total", "fleet_request_latency_seconds",
                "fleet_failover_seconds"} <= set(snap["metrics"])
        # Routes are per-replica-labelled.
        routes = snap["metrics"]["fleet_routes_total"]["samples"]
        assert all("replica" in s["labels"] and "kind" in s["labels"]
                   for s in routes)
    finally:
        router.close()


# ---------------- open-loop per-lane views (satellite 2) --------------

def test_run_open_loop_reports_per_scene_and_per_route_quantiles():
    disp = MicroBatchDispatcher(_echo, CFG,
                                slo=SLOPolicy(deadline_ms=30_000.0))
    try:
        # Warmup on a DIFFERENT lane: the run-local blocks must cover
        # exactly the run (lane histogram reset at run start) and a
        # stale pre-run lane must not appear as a count-0 NaN row.
        disp.infer_one(_frame(0), scene="warm", timeout=30.0)
        res = run_open_loop(
            disp,
            lambda i: (_frame(i), f"s{i % 2}", None),
            uniform_arrivals(300.0, 40),
            deadline_ms=30_000.0,
        )
    finally:
        disp.close()
    assert res["outcomes"]["served"] + res["outcomes"]["degraded"] == 40
    assert set(res["per_scene"]) == {"s0", "s1"}
    for rec in res["per_scene"].values():
        assert rec["count"] == 20
        assert rec["p50_ms"] > 0 and rec["p99_ms"] >= rec["p50_ms"] * 0.9
    assert set(res["per_route_k"]) == {"None"}
    assert res["per_route_k"]["None"]["count"] == 40
    json.dumps(res["per_scene"])


def test_abandoned_request_span_survives_late_worker_stamps():
    """Review regression: a request abandoned mid-dispatch (caller
    timeout while the worker is wedged) gets its terminal stamp from
    `_abandon`; when the worker unsticks, its late stage stamps must be
    INERT — the chain still reads stamps-to-terminal only, and the
    telescoping sum still equals the measured end-to-end latency."""
    from esac_tpu.serve import DeadlineExceededError

    inj = FaultInjector(_echo)
    release = threading.Event()
    slo = SLOPolicy(deadline_ms=60_000.0, watchdog_ms=60_000.0)
    disp = MicroBatchDispatcher(inj, CFG, slo=slo, trace=True)
    try:
        inj.stall_once(release)
        req = disp.submit(_frame(1.0), scene="s")
        with pytest.raises(DeadlineExceededError):
            req.get(0.3)  # abandon while the dispatch is wedged
        assert req.outcome == "expired"
        release.set()  # the worker unsticks and stamps late
        deadline = time.time() + 10
        while disp.slo_totals()["pending"] and time.time() < deadline:
            time.sleep(0.01)
        time.sleep(0.05)  # let any late stamp land
        eff_stages = [s for s, _ in req.spans._effective()]
        assert eff_stages[-1] == "expired"
        assert req.spans.total() == pytest.approx(req.t_done - req.t_submit)
        resid = abs(math.fsum(req.spans.durations().values())
                    - (req.t_done - req.t_submit))
        assert resid < 1e-9
    finally:
        release.set()
        disp.close()


def test_reset_stats_on_shared_registry_preserves_other_dispatcher():
    """Review regression: on a SHARED obs registry, one dispatcher's
    reset_stats must subtract only its OWN contribution — the other
    dispatcher's accounting invariant survives."""
    shared = MetricsRegistry()
    a = MicroBatchDispatcher(_echo, CFG, start_worker=False, obs=shared)
    b = MicroBatchDispatcher(_echo, CFG, start_worker=False, obs=shared)
    for i in range(4):
        a.infer_one(_frame(i), scene="sa")
    for i in range(6):
        b.infer_one(_frame(i), scene="sb")
    assert shared.get("serve_offered_total").total() == 10
    a.reset_stats()
    # b's history survives in the shared counters; a's is gone
    assert shared.get("serve_offered_total").total() == 6
    tb = b.slo_totals()
    assert tb["offered"] == 6 and tb["served"] == 6 and tb["pending"] == 0
    ta = a.slo_totals()
    # a's view now spans the shared registry (the documented aggregation
    # semantics) but must not have gone negative or inconsistent
    assert ta["offered"] == 6 and ta["served"] == 6
    assert b.dispatch_totals() == {("sb", None): 6}


# ---------------- dump CLI ----------------

def test_obs_cli_renders_artifact_and_bare_snapshots(tmp_path, capsys):
    from esac_tpu.obs.__main__ import main as obs_main

    r = MetricsRegistry()
    r.counter("req_total", "requests").inc(2, scene="s0")
    snap = r.snapshot()

    artifact = tmp_path / "artifact.json"
    artifact.write_text(json.dumps(
        {"metric": "x", "obs_provenance": {"obs_schema": OBS_SCHEMA,
                                           "fleet": snap}}
    ))
    assert obs_main(["--file", str(artifact)]) == 0
    page = capsys.readouterr().out
    assert "# TYPE req_total counter" in page

    bare = tmp_path / "snap.json"
    bare.write_text(json.dumps(snap))
    assert obs_main(["--file", str(bare), "--format", "json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["obs_schema"] == OBS_SCHEMA

    assert obs_main(["--file", str(tmp_path / "missing.json")]) == 2
    empty = tmp_path / "empty.json"
    empty.write_text("{}")
    assert obs_main(["--file", str(empty)]) == 2


# ---------------- concurrency: consistent, non-blocking snapshots -----

def test_concurrent_snapshots_consistent_and_admission_unblocked():
    """The R10 stress leg of the obs layer: serving threads race
    snapshot/export readers; every mid-traffic snapshot's outcome
    classes + pending must sum EXACTLY to offered, and the export
    surface must never corrupt or raise.

    graft-audit v3 rides the same leg with the runtime lock witness:
    the snapshot/export/collector machinery's ACTUAL acquisition edges
    must stay inside the committed .lock_graph.json order (the
    registry -> owner -> instrument order the obs module docstring
    states, now machine-checked at runtime too)."""
    import pathlib as _pathlib

    from esac_tpu.lint.lockgraph import LOCK_GRAPH_NAME, load_graph
    from esac_tpu.lint.witness import LockWitness

    cfg = RansacConfig(n_hyps=8, frame_buckets=(1, 4),
                       serve_max_wait_ms=1.0, serve_queue_depth=64)
    disp = MicroBatchDispatcher(_echo, cfg, trace=True,
                                slo=SLOPolicy(deadline_ms=60_000.0),
                                start_worker=False)
    # ISSUE 15: the timeline + rule engine ride the same stress leg —
    # attached BEFORE the witness so their leaf locks are wrapped and
    # the observed order check covers them.
    timeline = disp.obs.attach_timeline(window_s=0.02, max_windows=32)
    engine = disp.obs.attach_health_rules()
    # Warm the sync path once so the fleet latency/stage histogram
    # children exist for the witness to wrap, then re-base the books so
    # the exact-accounting assertions below stay exact.
    disp.infer_one(_frame(-1.0), scene="warm", timeout=60.0)
    disp.reset_stats()
    witness = LockWitness().attach_fleet(disp=disp)
    disp.start()
    n_callers, n_each = 3, 40
    errors: list[Exception] = []
    done = threading.Event()

    def caller(tid):
        try:
            for i in range(n_each):
                out = disp.infer_one(_frame(tid * 1000 + i),
                                     scene=f"s{tid}", timeout=60.0)
                assert float(out["echo"][0]) == tid * 1000 + i
        except Exception as e:  # noqa: BLE001 — surfaced in main thread
            errors.append(e)

    def reader():
        try:
            while not done.is_set():
                snap = disp.obs.snapshot()
                t = snap["collectors"]["serve_slo_totals"]
                total = (t["served"] + t["shed"] + t["expired"]
                         + t["degraded"] + t["failed"] + t["pending"])
                assert total == t["offered"], t
                assert "# TYPE" in render_prometheus(snap)
                json.dumps(snap)
                # ISSUE 15: tick + evaluate race the servers/readers too
                # (every tick takes instrument locks, every evaluate the
                # timeline + engine leaf locks — all witnessed).
                timeline.maybe_tick()
                engine.maybe_evaluate()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    callers = [threading.Thread(target=caller, args=(t,))
               for t in range(n_callers)]
    readers = [threading.Thread(target=reader) for _ in range(2)]
    for t in callers + readers:
        t.start()
    for t in callers:
        t.join(60)
    done.set()
    for t in readers:
        t.join(10)
    disp.close()
    assert errors == [], errors
    t = disp.slo_totals()
    assert t["served"] == n_callers * n_each == t["offered"]
    # graft-audit v3: observed acquisition edges ⊆ committed order, and
    # the publish-under-dispatch-lock edge was actually exercised.
    committed = load_graph(
        _pathlib.Path(__file__).resolve().parent.parent / LOCK_GRAPH_NAME
    )
    assert committed is not None, "no committed .lock_graph.json"
    witness.assert_subgraph(committed)
    assert any(src == "MicroBatchDispatcher._lock"
               for (src, _dst) in witness.edges())
    holds = witness.hold_summary()
    assert holds["MicroBatchDispatcher._lock"]["count"] > 0
    # ISSUE 15: the new leaf locks were exercised AND witnessed — and
    # the observed edge into the trace store (publication under the
    # dispatch lock) is exactly the committed nesting.
    for node in ("TraceStore._lock", "Timeline._lock",
                 "RuleEngine._lock"):
        assert holds[node]["count"] > 0, node
    assert ("MicroBatchDispatcher._lock", "TraceStore._lock") \
        in witness.edges()
    # The rule evaluation over live mid-traffic windows stayed quiet.
    assert engine.snapshot()["active"] == {}


# ---------------- ISSUE 15: causal traces / timeline / rules ----------

def _mk_fleet(trace_sample=1, watchdog_ms=60_000.0, n_reps=2):
    from esac_tpu.fleet import FleetPolicy, FleetRouter, Replica

    injs = [FaultInjector(_echo, tag=f"r{i}") for i in range(n_reps)]
    slo = SLOPolicy(deadline_ms=60_000.0, watchdog_ms=watchdog_ms,
                    watchdog_poll_ms=10.0)
    reps = [Replica(f"r{i}", MicroBatchDispatcher(inj, CFG, slo=slo))
            for i, inj in enumerate(injs)]
    router = FleetRouter(reps, FleetPolicy(poll_ms=2.0,
                                           trace_sample=trace_sample))
    return router, injs


def test_fleet_trace_telescopes_and_nests_dispatch_spans():
    """Tentpole acceptance: a sampled fleet request's trace partitions
    [t_submit, t_done] into routing / replica / outcome segments whose
    fsum is EXACTLY the end-to-end latency, with the replica dispatch
    riding as a child span carrying the dispatcher's own stage chain
    (which telescopes in ITS clock domain)."""
    router, _ = _mk_fleet()
    try:
        req = router.submit(_frame(1.0), scene="sA", deadline_ms=30_000)
        req.get(30.0)
        deadline = time.time() + 5
        while not (req.trace and req.trace.done) and time.time() < deadline:
            time.sleep(0.005)
        tr = req.trace
        assert tr is not None and tr.done and tr.outcome == "served"
        stages = [s for s, _ in tr.root.segments()]
        assert stages == ["routing", "replica", "served"]
        assert tr.residual() < 1e-9
        assert tr.total() == pytest.approx(req.t_done - req.t_submit)
        dsp = [s for s in tr.spans if s.kind == "dispatch"]
        assert len(dsp) == 1 and dsp[0].name == "replica:r0"
        # the child chain telescopes on its own: stage dts sum to span
        assert math.fsum(dt for _, dt in dsp[0].stages) == pytest.approx(
            dsp[0].t1 - dsp[0].t0)
        assert {"coalesced", "device", "served"} <= {
            s for s, _ in dsp[0].stages}
        # the routing decision rode as an event span
        kinds = [(s.name, s.annotations.get("route_kind"))
                 for s in tr.spans if s.name == "route_decision"]
        assert kinds and kinds[0][1] in ("cold", "affinity", "dense")
        # and the trace landed in the router's ring-bounded store
        store = router.obs.get_trace_store()
        assert any(t.trace_id == tr.trace_id for t in store.traces())
    finally:
        router.close()


def test_fleet_trace_telescopes_exactly_across_failover():
    """Satellite 3 acceptance: a watchdog-typed wedge fails the traced
    request over to the surviving replica and the trace STILL
    telescopes exactly — root stages show the failover sibling, the two
    dispatch spans link retry_of, and the quarantine event is
    annotated."""
    router, injs = _mk_fleet(watchdog_ms=200.0)
    try:
        # Seed the scene's home onto r0, then wedge exactly r0.
        router.infer_one(_frame(0.0), scene="sF", deadline_ms=30_000)
        home = router.scene_homes()["sF"][0]
        release = threading.Event()
        for inj in injs:
            inj.stall_once(release,
                           match=lambda ctx, t=home: ctx["tag"] == t)
        out = router.infer_one(_frame(2.0), scene="sF",
                               deadline_ms=30_000)
        assert float(out["echo"][0]) == 2.0
        release.set()
        store = router.obs.get_trace_store()
        fo = [t for t in store.traces() if t.done
              and len([s for s in t.spans if s.kind == "dispatch"]) > 1]
        assert fo, "no failed-over trace captured"
        tr = fo[-1]
        stages = [s for s, _ in tr.root.segments()]
        assert stages == ["routing", "replica", "failover_routing",
                          "replica", tr.outcome]
        assert tr.residual() < 1e-9
        dsp = [s for s in tr.spans if s.kind == "dispatch"]
        assert dsp[1].annotations["retry_of"] == dsp[0].span_id
        assert dsp[0].annotations["replica"] != dsp[1].annotations["replica"]
        events = {s.name for s in tr.spans if s.kind == "event"}
        assert "replica_fault" in events
    finally:
        release.set()
        router.close()


def test_trace_rides_host_tier_demand_fault_with_exact_telescoping():
    """Satellite 3: the registry fault path nests under a traced
    dispatch — a cold demand fault records a weight_fault span (disk
    source), a host-tier re-promotion records a host_tier one, and the
    request chain still telescopes exactly around the multi-ms fault."""
    from esac_tpu.registry import DeviceWeightCache
    from esac_tpu.registry.hosttier import HostWeightTier

    class Entry:
        def __init__(self, key):
            self.key = key

    loads = []

    def loader(entry):
        loads.append(entry.key)
        return {"w": np.full(4, 7.0, np.float32)}

    tier = HostWeightTier(compression="none")
    cache = DeviceWeightCache(loader, tier=tier)

    def infer(tree, scene=None, route_k=None):
        cache.get(Entry(("sc", 1)))
        return {"echo": tree["x"]}

    disp = MicroBatchDispatcher(infer, CFG, trace=True)
    try:
        req = disp.submit(_frame(1.0), scene="sc")
        req.get(30.0)
        tr = req.trace
        assert tr is not None and tr.done
        wf = [s for s in tr.spans if s.kind == "weight_fault"]
        assert len(wf) == 1 and wf[0].annotations["source"] == "disk"
        assert wf[0].annotations["coalesced"] is False
        assert dict(wf[0].stages)["read_disk"] > 0
        resid = abs(math.fsum(req.spans.durations().values())
                    - (req.t_done - req.t_submit))
        assert resid < 1e-9
        # Demote to the host tier; the next fault is a host-tier hit.
        cache.demote(("sc", 1))
        req2 = disp.submit(_frame(2.0), scene="sc")
        req2.get(30.0)
        wf2 = [s for s in req2.trace.spans if s.kind == "weight_fault"]
        assert wf2 and wf2[0].annotations["source"] == "host_tier"
        assert loads == [("sc", 1)]  # one disk read ever
        # Both traces landed in the dispatcher's store (slowest view).
        store = disp.obs.get_trace_store()
        assert store.added >= 2
        assert store.slowest(1)[0]["total_s"] > 0
    finally:
        disp.close()


def test_trace_annotates_prefetch_coalesced_demand_fault():
    """A demand fault that coalesces onto an in-flight PREFETCH-issued
    load is annotated as exactly that — at the cache level
    (coalesced_with=prefetch when the prefetch owns the device-promote
    future) and at the tier level (the prefetch_coalesced event when
    the prefetch owns the disk read via preload_host)."""
    from esac_tpu.obs import issuer_scope, trace_scope, Trace
    from esac_tpu.registry import DeviceWeightCache
    from esac_tpu.registry.hosttier import HostWeightTier

    class Entry:
        def __init__(self, key):
            self.key = key

    gate = threading.Event()

    def loader(entry):
        gate.wait(10.0)
        return {"w": np.zeros(2, np.float32)}

    tier = HostWeightTier(compression="none")
    cache = DeviceWeightCache(loader, tier=tier)

    def run_pair(prefetch_fn, entry):
        """Start the prefetch-issued load, then a traced demand fault
        racing it; release, join, return the demand's trace."""
        gate.clear()
        t_pf = threading.Thread(target=prefetch_fn)
        t_pf.start()
        deadline = time.time() + 5
        while not (cache.stats()["loads_in_flight"]
                   or tier.stats()["loads_in_flight"]) \
                and time.time() < deadline:
            time.sleep(0.002)
        tr = Trace(time.perf_counter(), scene=str(entry.key),
                   root_stage="admitted")
        res = {}

        def demand():
            with trace_scope([tr]):
                res["tree"] = cache.get(entry)

        t_d = threading.Thread(target=demand)
        t_d.start()
        time.sleep(0.05)
        gate.set()
        t_pf.join(10)
        t_d.join(10)
        assert res["tree"] is not None
        return tr

    # (a) prefetch owns the CACHE-level future (device promote): the
    # demand span is a coalesced wait annotated with the issuer.
    e1 = Entry(("pc", 1))

    def pf_dev():
        with issuer_scope("prefetch"):
            cache.get(e1)

    tr = run_pair(pf_dev, e1)
    wf = [s for s in tr.spans if s.kind == "weight_fault"]
    assert wf and wf[0].annotations["coalesced"] is True
    assert wf[0].annotations["coalesced_with"] == "prefetch"
    # (b) prefetch owns the TIER-level future (preload_host): the
    # demand owns the cache future but coalesces on the disk read —
    # the tier records the prefetch_coalesced event on the trace.
    e2 = Entry(("pc", 2))

    def pf_host():
        with issuer_scope("prefetch"):
            cache.preload_host(e2)

    tr2 = run_pair(pf_host, e2)
    events = {s.name for s in tr2.spans if s.kind == "event"}
    assert "prefetch_coalesced" in events
    wf2 = [s for s in tr2.spans if s.kind == "weight_fault"]
    assert wf2 and wf2[0].annotations["coalesced"] is False


def test_timeline_ring_exactly_window_bounded_under_10k_stream():
    """Satellite 3: 10k requests + many more ticks than the ring holds
    -> the ring holds EXACTLY max_windows windows, each window's counter
    deltas are exact (they sum to the totals), and the per-window
    histogram quantiles come from the window's own samples."""
    disp = MicroBatchDispatcher(_echo, CFG, start_worker=False)
    tl = disp.obs.attach_timeline(window_s=1e-9, max_windows=16,
                                  collectors=False)
    total = 10_000
    per_tick = 250
    tl.tick()
    for i in range(total // per_tick):
        for j in range(per_tick):
            disp.infer_one(_frame(j))
        tl.tick()
    wins = tl.windows()
    assert len(wins) == 16  # EXACTLY the bound, not one more
    assert tl.snapshot()["windows_retained"] == 16
    for w in wins:
        d = w["counters"]["serve_offered_total"][""]
        assert d == per_tick
        h = w["hist"]["serve_request_latency_seconds"][""]
        assert h["count"] == per_tick and h["p50"] > 0
        assert w["rates"]["serve_offered_total"][""] > 0
    assert disp.obs.get("serve_offered_total").total() == total
    disp.close()


def test_timeline_survives_reset_stats_and_histogram_reset():
    """The lifetime stream behind per-window deltas is monotone across
    reset_stats: the post-reset window's histogram count is the NEW
    observations only, and counter deltas follow the counter-reset
    convention (value below baseline -> delta = value) instead of
    recording a huge negative delta that would poison the burn-rate
    denominator for a whole slow horizon (review regression)."""
    disp = MicroBatchDispatcher(_echo, CFG, start_worker=False)
    tl = disp.obs.attach_timeline(window_s=1e-9, max_windows=8,
                                  collectors=False)
    for i in range(5):
        disp.infer_one(_frame(i))
    tl.tick()
    disp.reset_stats()  # clears window hists; lifetime keeps counting
    for i in range(3):
        disp.infer_one(_frame(i))
    w = tl.tick()
    h = w["hist"]["serve_request_latency_seconds"][""]
    assert h["count"] == 3
    # re-based counter: delta is the post-reset value (3), never -2.
    assert w["counters"]["serve_offered_total"][""] == 3
    assert all(d >= 0 for vals in w["counters"].values()
               for d in vals.values()), w["counters"]
    assert all(r >= 0 for vals in w["rates"].values()
               for r in vals.values())
    disp.close()


def test_per_window_quantile_underflow_reports_floor_not_inf():
    """Review regression: a window whose rank lands in the underflow
    bucket reports the bucket floor, never +inf (which would leak
    non-JSON-standard tokens into window records)."""
    h = StreamingHistogram(lo=1e-3)
    h.observe(5e-4)
    h.observe(5e-4)
    counts, n, _ = h.lifetime()
    q = h.quantile_from_counts(counts, n, 0.5)
    assert q == 1e-3 and math.isfinite(q)


def _synthetic_timeline(registry):
    from esac_tpu.obs.timeline import Timeline

    return Timeline(registry, window_s=1e-9, max_windows=64)


def test_rule_engine_burn_rate_golden_trip_and_recovery():
    from esac_tpu.obs import MetricsRegistry, default_rules, RuleEngine

    r = MetricsRegistry()
    offered = r.counter("serve_offered_total")
    outcomes = r.counter("serve_outcomes_total")
    tl = _synthetic_timeline(r)
    eng = RuleEngine(tl, default_rules(), registry=r)
    tl.tick()
    # Healthy windows: plenty offered, nothing bad -> quiet.
    for _ in range(4):
        offered.inc(50)
        outcomes.inc(50, outcome="served")
        tl.tick()
    assert eng.evaluate() == []
    # Burn: 30% shed across fast AND slow windows -> trips.
    for _ in range(3):
        offered.inc(50)
        outcomes.inc(35, outcome="served")
        outcomes.inc(15, outcome="shed")
        tl.tick()
    firing = eng.evaluate()
    assert [a.rule for a in firing] == ["slo_burn_rate"]
    assert firing[0].value >= 0.1 and firing[0].severity == "page"
    # Edge-triggering: still firing -> no NEW raise event.
    n_events = len(eng.alerts())
    eng.evaluate()
    assert len(eng.alerts()) == n_events
    # Recovery: healthy windows push the fast frac back down -> clear.
    for _ in range(6):
        offered.inc(200)
        outcomes.inc(200, outcome="served")
        tl.tick()
    assert eng.evaluate() == []
    edges = [e.get("edge") for e in eng.alerts()]
    assert edges == ["raise", "clear"]
    # The instruments published: counter + active gauge.
    assert r.get("health_alerts_total").get(rule="slo_burn_rate",
                                            edge="raise") == 1
    assert r.get("health_alert_active").get(rule="slo_burn_rate") == 0.0


def test_rule_engine_bad_frac_slope_golden_trip():
    from esac_tpu.obs import MetricsRegistry, RuleEngine
    from esac_tpu.obs.rules import BadFracSlopeRule

    r = MetricsRegistry()
    bad_frac = {"v": 0.0}
    r.register_collector(
        "scene_health",
        lambda: {"scenes": {"s0@v1": {"bad_frac": bad_frac["v"]},
                            "s1@v1": {"bad_frac": 0.01}}},
    )
    tl = _synthetic_timeline(r)
    eng = RuleEngine(tl, (BadFracSlopeRule(),), registry=r)
    # Flat series -> quiet (a noisy-but-flat breaker must not fire).
    for _ in range(8):
        tl.tick()
    assert eng.evaluate() == []
    # Steady drift up, well under any trip threshold -> fires on SLOPE.
    for i in range(8):
        bad_frac["v"] = 0.05 * i
        tl.tick()
    firing = eng.evaluate()
    assert len(firing) == 1
    a = firing[0]
    assert a.rule == "scene_bad_frac_slope"
    assert "s0@v1" in a.labels["path"]  # the drifting scene, not s1
    assert a.value >= 0.02


def test_rule_engine_quiet_fleet_raises_nothing():
    """Golden quiet case: a healthy serving fleet (real dispatcher
    traffic, all served) evaluates the FULL default catalog to zero
    alerts, and the snapshot carries empty active/events blocks."""
    disp = MicroBatchDispatcher(_echo, CFG, start_worker=False)
    tl = disp.obs.attach_timeline(window_s=1e-9, max_windows=32)
    eng = disp.obs.attach_health_rules()
    for i in range(40):
        disp.infer_one(_frame(i), scene=f"s{i % 2}")
        if i % 10 == 9:
            tl.tick()
    assert eng.evaluate() == []
    snap = eng.snapshot()
    assert snap["active"] == {} and snap["events"] == []
    assert set(snap["rules"]) == {
        "slo_burn_rate", "scene_bad_frac_slope", "prefetch_waste",
        "affinity_sag", "queue_knee",
    }
    full = disp.obs.snapshot()
    assert full["collectors"]["health_alerts"]["active"] == {}
    json.dumps(full)
    disp.close()


# ---------------- ISSUE 15 satellite: export/CLI coverage --------------

def test_every_registered_collector_is_known_and_renders():
    """Schema pin: a FULL fleet's registered collector set must be
    covered by export.KNOWN_COLLECTORS (a NEW collector cannot land
    unrendered — adding it forces a reviewed entry here), and every
    pinned numeric field renders as a real Prometheus sample."""
    import pathlib

    from esac_tpu.lint.witness import LockWitness, OutcomeWitness
    from esac_tpu.obs.export import KNOWN_COLLECTORS
    from esac_tpu.registry import SceneManifest, SceneRegistry
    from esac_tpu.fleet import FleetPolicy, FleetRouter, Replica

    reg = SceneRegistry(SceneManifest())
    disp = reg.dispatcher(CFG, start_worker=False)
    reg.attach_prefetcher(start=False)
    reg._prefetcher.bind_obs(disp.obs)
    if reg.cache.tier is None:
        from esac_tpu.registry.hosttier import HostWeightTier

        HostWeightTier(compression="none").bind_obs(disp.obs)
    LockWitness().bind_obs(disp.obs)
    OutcomeWitness.from_repo(
        pathlib.Path(__file__).resolve().parents[1]).bind_obs(disp.obs)
    disp.obs.trace_store()
    disp.obs.attach_health_rules()
    router = FleetRouter(
        [Replica("r0", MicroBatchDispatcher(_echo, CFG,
                                            slo=SLOPolicy()))],
        FleetPolicy(poll_ms=5.0), obs=disp.obs, start=False,
    )
    from esac_tpu.retrieval import RetrievalFront, SceneIndex

    # ISSUE 18: the image-tier front registers the "retrieval" collector
    # through attach_retrieval (stats-only here — the forward fn is
    # never invoked, so a stub keeps jax out of this test).
    router.attach_retrieval(RetrievalFront(
        lambda *a: None, None, SceneIndex(capacity=4, embed_dim=4)))
    # ISSUE 20: the session lane registers the "session" collector.
    from esac_tpu.serve import SessionRouter

    SessionRouter(disp)
    snap = disp.obs.snapshot()
    registered = set(snap["collectors"])
    unknown = registered - set(KNOWN_COLLECTORS)
    assert not unknown, (
        f"collectors {sorted(unknown)} not in export.KNOWN_COLLECTORS — "
        "add them (and their key fields) so they render"
    )
    page = render_prometheus(snap)
    for cname in registered:
        assert f"# COLLECTOR {cname} " in page, cname
        for field in KNOWN_COLLECTORS[cname]:
            block = snap["collectors"][cname]
            if isinstance(block, dict) and field in block \
                    and isinstance(block[field], (int, float)) \
                    and not isinstance(block[field], bool):
                assert (f'esac_collector_value{{collector="{cname}",'
                        f'path="{field}"}}') in page, (cname, field)
    router.close(close_replicas=True)
    disp.close()


def test_prometheus_renders_collector_numeric_leaves():
    r = MetricsRegistry()
    r.register_collector("weight_cache",
                         lambda: {"hits": 5, "nested": {"x": 2.5},
                                  "skip": "str", "flag": True})
    page = render_prometheus(r.snapshot())
    assert 'esac_collector_value{collector="weight_cache",path="hits"} 5.0' \
        in page
    assert ('esac_collector_value{collector="weight_cache",'
            'path="nested.x"} 2.5') in page
    assert "flag" not in page and "skip" not in page.replace(
        "# COLLECTOR", "")


def test_obs_cli_traces_mode_renders_slowest(tmp_path, capsys):
    from esac_tpu.obs.__main__ import main as obs_main

    disp = MicroBatchDispatcher(_echo, CFG, trace=True)
    try:
        for i in range(4):
            disp.infer_one(_frame(i), scene=f"s{i % 2}", timeout=30.0)
    finally:
        disp.close()
    snap = disp.obs.snapshot()
    f = tmp_path / "snap.json"
    f.write_text(json.dumps(snap))
    assert obs_main(["--file", str(f), "--traces", "2"]) == 0
    out = capsys.readouterr().out
    assert "slowest sampled traces" in out
    assert "trace t" in out and "served" in out
    # a snapshot without traces says so instead of crashing
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps(MetricsRegistry().snapshot()))
    assert obs_main(["--file", str(bare), "--traces"]) == 0
    assert "no sampled traces" in capsys.readouterr().out


def test_run_open_loop_records_trace_ids_and_exemplars():
    disp = MicroBatchDispatcher(_echo, CFG, trace=True,
                                slo=SLOPolicy(deadline_ms=30_000.0))
    try:
        res = run_open_loop(
            disp, lambda i: (_frame(i), f"s{i % 2}", None),
            uniform_arrivals(300.0, 20), deadline_ms=30_000.0,
        )
    finally:
        disp.close()
    ids = res["per_request_trace_ids"]
    assert len(ids) == 20 and all(isinstance(t, str) for t in ids)
    assert len(set(ids)) == 20
    ex = res["exemplar_slow_traces"]
    assert ex and ex[0]["total_s"] > 0
    assert ex[0]["trace_id"] in ids
    json.dumps(res["exemplar_slow_traces"])


def test_snapshot_and_admission_never_block_on_wedged_dispatch():
    """A wedged in-flight dispatch (the observed relay-stall mode) must
    not make observability part of the outage: snapshot/export return
    promptly and submits still admit while the worker is stuck."""
    inj = FaultInjector(_echo)
    release = threading.Event()
    slo = SLOPolicy(deadline_ms=60_000.0, watchdog_ms=60_000.0)
    disp = MicroBatchDispatcher(inj, CFG, slo=slo, trace=True)
    try:
        inj.stall_once(release)
        wedged = disp.submit(_frame(1.0), scene="bad")
        deadline = time.time() + 10
        while disp.slo_totals()["pending"] < 1 and time.time() < deadline:
            time.sleep(0.01)
        t0 = time.perf_counter()
        snap = disp.obs.snapshot()
        dt_snap = time.perf_counter() - t0
        assert dt_snap < 2.0, dt_snap
        t = snap["collectors"]["serve_slo_totals"]
        assert t["pending"] >= 1 and t["offered"] >= 1
        t0 = time.perf_counter()
        queued = disp.submit(_frame(2.0), scene="good")
        assert time.perf_counter() - t0 < 0.5  # admission not blocked
        release.set()
        queued.get(60.0)
        wedged.get(60.0)
    finally:
        release.set()
        disp.close()


# ---------------- batched publishes (ISSUE 17 host hot path) ----------------
#
# The serving hot path publishes per-dispatch (observe_many / counter
# inc(n=...)), not per-request.  The contract: the batched path is
# sample-for-sample IDENTICAL to a loop of scalar observes — same bucket
# increments, same lifetime stream, epoch rotation after every sample —
# so snapshots cannot tell the two apart.

def test_histogram_observe_many_identical_to_sequential():
    import random

    rng = random.Random(5)
    xs = [rng.lognormvariate(-5.0, 1.0) for _ in range(5000)]
    xs[100] = float("nan")   # the clamp cases ride the bulk path too
    xs[200] = -1.0
    xs[300] = float("inf")
    a = StreamingHistogram(window=700, epochs=3)
    b = StreamingHistogram(window=700, epochs=3)
    for x in xs:
        a.observe(x)
    i = 0
    for size in (1, 2, 3, 499, 1200, 7, 5000):  # 1200 > epoch cap: the
        b.observe_many(xs[i:i + size])          # rotation lands MID-batch
        i += size
    b.observe_many([])  # empty batch is a no-op, not an epoch event
    assert a._counts == b._counts
    assert a._stats == b._stats
    assert a._life_counts == b._life_counts
    assert a._life_n == b._life_n and a._life_sum == b._life_sum
    assert a.summary() == b.summary()


def test_histogram_vec_observe_many_identical_to_sequential():
    ra, rb = MetricsRegistry(), MetricsRegistry()
    a = ra.histogram("h_seconds", "help")
    b = rb.histogram("h_seconds", "help")
    xs = [1e-3 * (1 + i % 13) for i in range(400)]
    for x in xs:
        a.observe(x, scene="s0", route_k="2")
    b.observe_many(xs, scene="s0", route_k="2")
    assert a.labelsets() == b.labelsets()
    assert a.summary(scene="s0", route_k="2") == \
        b.summary(scene="s0", route_k="2")


def test_batched_latency_publish_counts_every_served_request():
    """The dispatcher's per-dispatch bulk publish must still account one
    latency sample and one outcome per request, not per dispatch."""
    disp = MicroBatchDispatcher(_echo, CFG)
    reqs = [disp.submit(_frame(float(i)), scene="s") for i in range(9)]
    for r in reqs:
        r.get(timeout=30.0)
    disp.close()
    assert disp.slo_totals()["served"] == 9
    assert disp.obs.get("serve_request_latency_seconds").summary()["count"] == 9
    lane = disp.obs.get("serve_lane_latency_seconds")
    assert lane.summary(scene="s", route_k=None)["count"] == 9
