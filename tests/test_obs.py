"""Observability-layer tests (ISSUE 10, DESIGN.md §14).

The load-bearing claims:

- the streaming-quantile histogram that replaced ``latency_quantiles``'s
  sort-the-whole-deque reports p50/p99 within a PINNED tolerance of the
  exact nearest-rank values, at fixed memory, over a recent window;
- every traced request's span durations sum (math.fsum) to its measured
  end-to-end latency, and tracing off leaves the request path unstamped;
- the four legacy snapshot surfaces (``slo_totals``,
  ``dispatch_totals``, ``SceneRegistry.health``,
  ``DeviceWeightCache.stats``) keep their pre-refactor shapes while
  being views over / collectors of the unified obs registry;
- ``obs.snapshot()`` is ``json.dumps``-able, stays CONSISTENT
  mid-traffic (outcome classes + pending sum to offered in every
  concurrent read) and never blocks admission — even while a dispatch is
  wedged.
"""

import json
import math
import threading
import time

import numpy as np
import pytest

from esac_tpu.obs import (
    OBS_SCHEMA,
    MetricsRegistry,
    SpanChain,
    StreamingHistogram,
    jsonable,
    render_prometheus,
)
from esac_tpu.ransac import RansacConfig
from esac_tpu.serve import (
    FaultInjector,
    MicroBatchDispatcher,
    SLOPolicy,
    run_open_loop,
    uniform_arrivals,
)

CFG = RansacConfig(n_hyps=8, frame_buckets=(1, 4), serve_max_wait_ms=1.0)

# The pinned histogram tolerance: bucket growth 1.07 bounds the relative
# quantile error at sqrt(1.07)-1 ~= 3.4%; 5% leaves margin for the
# nearest-rank discretization at small sample counts.
QUANTILE_RTOL = 0.05


def _echo(tree, scene=None, route_k=None):
    return {"echo": tree["x"]}


def _frame(v=0.0):
    return {"x": np.full(2, v, np.float32)}


def _exact_rank(sorted_xs, q):
    return sorted_xs[min(len(sorted_xs) - 1, round(q * (len(sorted_xs) - 1)))]


# ---------------- streaming histogram (satellite 1) ----------------

def test_histogram_quantiles_within_pinned_tolerance():
    import random

    rng = random.Random(0)
    h = StreamingHistogram(window=5000)
    xs = [rng.lognormvariate(-5.0, 1.0) for _ in range(20_000)]
    for x in xs:
        h.observe(x)
    recent = sorted(xs[-5000:])
    for q in (0.5, 0.9, 0.99):
        exact = _exact_rank(recent, q)
        est = h.quantile(q)
        assert abs(est - exact) / exact <= QUANTILE_RTOL, (q, exact, est)


def test_histogram_window_tracks_recent_distribution():
    import random

    rng = random.Random(1)
    h = StreamingHistogram(window=2000)
    for _ in range(10_000):
        h.observe(rng.lognormvariate(-6.0, 0.3))  # ~2.5ms scale
    for _ in range(4000):  # > window: the old regime must rotate out
        h.observe(rng.lognormvariate(-3.0, 0.3))  # ~50ms scale
    p50 = h.quantile(0.5)
    assert 0.02 < p50 < 0.12, p50  # the NEW scale, not the old one


def test_histogram_fixed_memory_and_edges():
    h = StreamingHistogram(window=100, epochs=4)
    assert math.isnan(h.quantile(0.5))
    for i in range(100_000):
        h.observe(1e-3 * (1 + (i % 7)))
    # memory: at most `epochs` bucket arrays, however many samples landed
    assert len(h._counts) <= 4
    # non-finite / non-positive samples clamp, never raise or corrupt
    h.observe(float("nan"))
    h.observe(-1.0)
    h.observe(float("inf"))
    assert h.quantile(0.5) > 0
    s = h.summary()
    assert s["count"] > 0 and s["p50"] == h.quantile(0.5)
    # single-sample histogram reports the sample exactly (min/max clamp)
    h2 = StreamingHistogram()
    h2.observe(0.25)
    assert h2.quantile(0.5) == pytest.approx(0.25)


# ---------------- registry / instruments / export ----------------

def test_registry_instruments_idempotent_and_kind_checked():
    r = MetricsRegistry()
    c = r.counter("x_total", "help")
    assert r.counter("x_total") is c
    with pytest.raises(ValueError):
        r.gauge("x_total")
    c.inc(2, lane="a")
    c.inc(lane="b")
    assert c.total() == 3
    assert c.get(lane="a") == 2
    c.rebase(7, lane="a")
    assert c.get(lane="a") == 7
    c.reset()
    assert c.total() == 0
    g = r.gauge("g")
    g.set(1.5, k="v")
    assert g.get(k="v") == 1.5
    assert math.isnan(g.get(k="other"))


def test_registry_adopting_shared_instruments():
    a, b = MetricsRegistry(), MetricsRegistry()
    c = a.counter("shared_total")
    b.register(c)
    c.inc(5)
    assert b.get("shared_total").total() == 5
    b.register(c)  # re-adopt: no-op
    with pytest.raises(ValueError):
        b.register(MetricsRegistry().counter("shared_total"))


def test_snapshot_json_dumpable_with_hostile_collectors():
    import collections

    r = MetricsRegistry()
    r.counter("c_total").inc(scene=None, route_k=2)
    r.histogram("h_seconds", window=10).observe(0.01, stage="device")
    r.register_collector("tuple_keys", lambda: {("s0", None): 1})
    r.register_collector("numpyish", lambda: {"v": np.float32(1.5),
                                              "n": np.int64(3)})
    r.register_collector("dequeish",
                         lambda: collections.deque([1, 2], maxlen=4))
    r.register_collector("sick", lambda: 1 / 0)
    snap = r.snapshot()
    text = json.dumps(snap)  # the contract: NEVER raises
    assert snap["obs_schema"] == OBS_SCHEMA
    assert snap["collectors"]["tuple_keys"] == {"('s0', None)": 1}
    assert snap["collectors"]["numpyish"] == {"v": 1.5, "n": 3}
    assert snap["collectors"]["dequeish"] == [1, 2]
    assert "ZeroDivisionError" in snap["collectors"]["sick"]["error"]
    assert "c_total" in text and "h_seconds" in text


def test_render_prometheus_format():
    r = MetricsRegistry()
    r.counter("req_total", "requests").inc(3, scene="s0")
    r.histogram("lat_seconds", window=10).observe(0.25)
    page = r.render_prometheus()
    assert "# TYPE req_total counter" in page
    assert 'req_total{scene="s0"} 3.0' in page
    assert "# TYPE lat_seconds summary" in page
    assert 'lat_seconds{quantile="0.5"}' in page
    assert "lat_seconds_count" in page


def test_jsonable_stringifies_odd_keys_and_leaves():
    out = jsonable({(1, None): {np.float64(2.0), "x"}, "a": (1, 2)})
    json.dumps(out)
    assert out["(1, None)"] is not None and out["a"] == [1, 2]


# ---------------- span chains ----------------

def test_span_chain_durations_telescope():
    ch = SpanChain("admitted", 10.0)
    ch.stamp("coalesced", 10.5)
    ch.stamp("staged", 10.6)
    ch.stamp("staged", 10.9)  # retry re-stamp: aggregation must survive
    ch.stamp("served", 11.25)
    d = ch.durations()
    assert d["staged"] == pytest.approx(0.4)
    assert math.fsum(d.values()) == pytest.approx(ch.total())
    assert ch.residual() < 1e-12
    assert ch.total() == pytest.approx(1.25)


def test_traced_dispatcher_spans_sum_to_measured_latency():
    disp = MicroBatchDispatcher(_echo, CFG, trace=True)
    try:
        reqs = [disp.submit(_frame(i), scene=f"s{i % 2}") for i in range(8)]
        for r in reqs:
            r.get(60.0)
        for r in reqs:
            stages = [s for s, _ in r.spans.stamps]
            assert stages[0] == "admitted" and stages[-1] == "served"
            assert {"coalesced", "staged", "dispatched", "device",
                    "sliced"} <= set(stages)
            # the acceptance pin: per-stage durations sum EXACTLY (fsum)
            # to the measured end-to-end latency
            resid = abs(math.fsum(r.spans.durations().values())
                        - (r.t_done - r.t_submit))
            assert resid < 1e-9, (stages, resid)
        stage_hist = disp.obs.get("serve_stage_seconds")
        for stage in ("coalesced", "staged", "dispatched", "device",
                      "sliced", "served"):
            assert stage_hist.count(stage=stage) == len(reqs), stage
    finally:
        disp.close()


def test_tracing_off_leaves_requests_unstamped_but_metrics_on():
    disp = MicroBatchDispatcher(_echo, CFG)
    try:
        req = disp.submit(_frame(1.0))
        req.get(60.0)
        assert req.spans is None
        assert disp.obs.get("serve_stage_seconds").count() == 0
        assert disp.obs.get("serve_offered_total").total() == 1
        assert disp.obs.get("serve_outcomes_total").get(outcome="served") == 1
    finally:
        disp.close()


# ---------------- legacy snapshot surfaces: exact-compat pins ----------

def test_slo_and_dispatch_views_match_legacy_attributes():
    disp = MicroBatchDispatcher(_echo, CFG, start_worker=False)
    for i in range(60):
        disp.infer_one(_frame(i), scene=f"s{i % 3}",
                       route_k=(i % 2) or None)
    t = disp.slo_totals()
    assert set(t) == {"offered", "served", "shed", "expired", "degraded",
                      "failed", "pending"}
    assert all(isinstance(v, int) for v in t.values())
    # the view and the legacy attributes tell ONE story
    assert t["offered"] == disp.offered == 60
    assert t["served"] == disp.outcome_counts["served"] == 60
    totals = disp.dispatch_totals()
    assert totals == dict(disp.dispatch_counts)
    assert all(isinstance(k, tuple) and len(k) == 2 for k in totals)
    # satellite 1: the histogram-backed quantiles stay within the pinned
    # tolerance of exact nearest-rank over the SAME window
    lat = sorted(disp.latencies_s)
    q = disp.latency_quantiles()
    assert set(q) == {0.5, 0.99}
    for p, est in q.items():
        exact = _exact_rank(lat, p)
        assert abs(est - exact) / exact <= QUANTILE_RTOL, (p, exact, est)


def test_reset_stats_rebases_obs_views_too():
    disp = MicroBatchDispatcher(_echo, CFG, start_worker=False)
    for i in range(5):
        disp.infer_one(_frame(i), scene="s")
    disp.reset_stats()
    t = disp.slo_totals()
    assert t["offered"] == 0 and t["served"] == 0 and t["pending"] == 0
    assert disp.dispatch_totals() == {}
    assert math.isnan(disp.latency_quantiles()[0.5])
    disp.infer_one(_frame(9), scene="s")
    t = disp.slo_totals()
    assert t["offered"] == t["served"] == 1


def test_cache_stats_and_registry_health_shapes_pinned():
    from esac_tpu.registry import (
        DeviceWeightCache, SceneManifest, SceneRegistry,
    )

    cache = DeviceWeightCache(lambda e: {})
    assert set(cache.stats()) == {
        "hits", "misses", "evictions", "resident", "bytes_in_use",
        "budget_bytes", "load_failures", "loads_in_flight",
        # Tier hierarchy classes (ISSUE 13, DESIGN.md §17): present —
        # zero-valued — on tierless caches too, so monitors see one
        # schema fleet-wide.
        "host_hits", "disk_loads", "demotions",
    }
    reg = SceneRegistry(SceneManifest())
    h = reg.health()
    assert set(h) == {"scenes", "canaries", "events"}
    assert h["scenes"] == {} and h["canaries"] == {} and h["events"] == []
    json.dumps(h)


def test_scene_registry_binds_into_dispatcher_obs():
    from esac_tpu.registry import SceneManifest, SceneRegistry

    reg = SceneRegistry(SceneManifest())
    disp = reg.dispatcher(CFG, start_worker=False)
    snap = disp.obs.snapshot()
    assert {"serve_slo_totals", "serve_dispatch_totals",
            "serve_quarantined_lanes", "scene_health",
            "weight_cache"} <= set(snap["collectors"])
    # shared instrument OBJECTS, not copies: one fleet truth
    assert disp.obs.get("registry_health_events_total") \
        is reg.obs.get("registry_health_events_total")
    assert snap["collectors"]["scene_health"]["scenes"] == {}
    assert snap["collectors"]["weight_cache"]["resident"] == 0
    json.dumps(snap)
    # a second dispatcher over the same registry adopts the same
    # instruments without error, but keeps PRIVATE serve accounting
    disp2 = reg.dispatcher(CFG, start_worker=False)
    assert disp2.obs is not disp.obs
    assert disp2.obs.get("registry_health_events_total") \
        is reg.obs.get("registry_health_events_total")


def test_fleet_snapshot_per_replica_merge_shape_pinned():
    """ISSUE 14: a FleetRouter's ``obs.snapshot()`` carries the
    per-replica-labelled fleet merge — every replica's serve accounting
    under its name, the affinity table, the route counts and the fleet
    accounting — json-dumpable, shapes pinned (the driver/monitor
    contract, like the cache/health shapes above)."""
    import numpy as np

    from esac_tpu.fleet import FleetPolicy, FleetRouter, Replica

    def echo(tree, scene=None, route_k=None):
        return {"echo": tree["x"]}

    reps = [
        Replica(f"r{i}", MicroBatchDispatcher(echo, CFG, slo=SLOPolicy()))
        for i in range(2)
    ]
    router = FleetRouter(reps, FleetPolicy(poll_ms=2.0))
    try:
        for i in range(4):
            router.infer_one({"x": np.full(2, float(i), np.float32)},
                             scene=f"s{i % 2}", deadline_ms=5_000)
        snap = router.obs.snapshot()
        json.dumps(snap)
        assert "fleet" in snap["collectors"]
        fleet = snap["collectors"]["fleet"]
        assert set(fleet) == {"replicas", "scene_homes", "route_counts",
                              "accounting"}
        assert set(fleet["replicas"]) == {"r0", "r1"}
        for block in fleet["replicas"].values():
            assert set(block) == {"slo", "quarantined", "inflight"}
            assert set(block["slo"]) == {"offered", "served", "shed",
                                         "expired", "degraded", "failed",
                                         "pending"}
        acc = fleet["accounting"]
        assert set(acc) == {"offered", "served", "shed", "expired",
                            "degraded", "failed", "pending"}
        assert (acc["served"] + acc["shed"] + acc["expired"]
                + acc["degraded"] + acc["failed"] + acc["pending"]
                == acc["offered"] == 4)
        # The fleet instruments ride the same registry.
        assert {"fleet_offered_total", "fleet_outcomes_total",
                "fleet_routes_total", "fleet_failovers_total",
                "fleet_events_total", "fleet_request_latency_seconds",
                "fleet_failover_seconds"} <= set(snap["metrics"])
        # Routes are per-replica-labelled.
        routes = snap["metrics"]["fleet_routes_total"]["samples"]
        assert all("replica" in s["labels"] and "kind" in s["labels"]
                   for s in routes)
    finally:
        router.close()


# ---------------- open-loop per-lane views (satellite 2) --------------

def test_run_open_loop_reports_per_scene_and_per_route_quantiles():
    disp = MicroBatchDispatcher(_echo, CFG,
                                slo=SLOPolicy(deadline_ms=30_000.0))
    try:
        # Warmup on a DIFFERENT lane: the run-local blocks must cover
        # exactly the run (lane histogram reset at run start) and a
        # stale pre-run lane must not appear as a count-0 NaN row.
        disp.infer_one(_frame(0), scene="warm", timeout=30.0)
        res = run_open_loop(
            disp,
            lambda i: (_frame(i), f"s{i % 2}", None),
            uniform_arrivals(300.0, 40),
            deadline_ms=30_000.0,
        )
    finally:
        disp.close()
    assert res["outcomes"]["served"] + res["outcomes"]["degraded"] == 40
    assert set(res["per_scene"]) == {"s0", "s1"}
    for rec in res["per_scene"].values():
        assert rec["count"] == 20
        assert rec["p50_ms"] > 0 and rec["p99_ms"] >= rec["p50_ms"] * 0.9
    assert set(res["per_route_k"]) == {"None"}
    assert res["per_route_k"]["None"]["count"] == 40
    json.dumps(res["per_scene"])


def test_abandoned_request_span_survives_late_worker_stamps():
    """Review regression: a request abandoned mid-dispatch (caller
    timeout while the worker is wedged) gets its terminal stamp from
    `_abandon`; when the worker unsticks, its late stage stamps must be
    INERT — the chain still reads stamps-to-terminal only, and the
    telescoping sum still equals the measured end-to-end latency."""
    from esac_tpu.serve import DeadlineExceededError

    inj = FaultInjector(_echo)
    release = threading.Event()
    slo = SLOPolicy(deadline_ms=60_000.0, watchdog_ms=60_000.0)
    disp = MicroBatchDispatcher(inj, CFG, slo=slo, trace=True)
    try:
        inj.stall_once(release)
        req = disp.submit(_frame(1.0), scene="s")
        with pytest.raises(DeadlineExceededError):
            req.get(0.3)  # abandon while the dispatch is wedged
        assert req.outcome == "expired"
        release.set()  # the worker unsticks and stamps late
        deadline = time.time() + 10
        while disp.slo_totals()["pending"] and time.time() < deadline:
            time.sleep(0.01)
        time.sleep(0.05)  # let any late stamp land
        eff_stages = [s for s, _ in req.spans._effective()]
        assert eff_stages[-1] == "expired"
        assert req.spans.total() == pytest.approx(req.t_done - req.t_submit)
        resid = abs(math.fsum(req.spans.durations().values())
                    - (req.t_done - req.t_submit))
        assert resid < 1e-9
    finally:
        release.set()
        disp.close()


def test_reset_stats_on_shared_registry_preserves_other_dispatcher():
    """Review regression: on a SHARED obs registry, one dispatcher's
    reset_stats must subtract only its OWN contribution — the other
    dispatcher's accounting invariant survives."""
    shared = MetricsRegistry()
    a = MicroBatchDispatcher(_echo, CFG, start_worker=False, obs=shared)
    b = MicroBatchDispatcher(_echo, CFG, start_worker=False, obs=shared)
    for i in range(4):
        a.infer_one(_frame(i), scene="sa")
    for i in range(6):
        b.infer_one(_frame(i), scene="sb")
    assert shared.get("serve_offered_total").total() == 10
    a.reset_stats()
    # b's history survives in the shared counters; a's is gone
    assert shared.get("serve_offered_total").total() == 6
    tb = b.slo_totals()
    assert tb["offered"] == 6 and tb["served"] == 6 and tb["pending"] == 0
    ta = a.slo_totals()
    # a's view now spans the shared registry (the documented aggregation
    # semantics) but must not have gone negative or inconsistent
    assert ta["offered"] == 6 and ta["served"] == 6
    assert b.dispatch_totals() == {("sb", None): 6}


# ---------------- dump CLI ----------------

def test_obs_cli_renders_artifact_and_bare_snapshots(tmp_path, capsys):
    from esac_tpu.obs.__main__ import main as obs_main

    r = MetricsRegistry()
    r.counter("req_total", "requests").inc(2, scene="s0")
    snap = r.snapshot()

    artifact = tmp_path / "artifact.json"
    artifact.write_text(json.dumps(
        {"metric": "x", "obs_provenance": {"obs_schema": OBS_SCHEMA,
                                           "fleet": snap}}
    ))
    assert obs_main(["--file", str(artifact)]) == 0
    page = capsys.readouterr().out
    assert "# TYPE req_total counter" in page

    bare = tmp_path / "snap.json"
    bare.write_text(json.dumps(snap))
    assert obs_main(["--file", str(bare), "--format", "json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["obs_schema"] == OBS_SCHEMA

    assert obs_main(["--file", str(tmp_path / "missing.json")]) == 2
    empty = tmp_path / "empty.json"
    empty.write_text("{}")
    assert obs_main(["--file", str(empty)]) == 2


# ---------------- concurrency: consistent, non-blocking snapshots -----

def test_concurrent_snapshots_consistent_and_admission_unblocked():
    """The R10 stress leg of the obs layer: serving threads race
    snapshot/export readers; every mid-traffic snapshot's outcome
    classes + pending must sum EXACTLY to offered, and the export
    surface must never corrupt or raise.

    graft-audit v3 rides the same leg with the runtime lock witness:
    the snapshot/export/collector machinery's ACTUAL acquisition edges
    must stay inside the committed .lock_graph.json order (the
    registry -> owner -> instrument order the obs module docstring
    states, now machine-checked at runtime too)."""
    import pathlib as _pathlib

    from esac_tpu.lint.lockgraph import LOCK_GRAPH_NAME, load_graph
    from esac_tpu.lint.witness import LockWitness

    cfg = RansacConfig(n_hyps=8, frame_buckets=(1, 4),
                       serve_max_wait_ms=1.0, serve_queue_depth=64)
    disp = MicroBatchDispatcher(_echo, cfg, trace=True,
                                slo=SLOPolicy(deadline_ms=60_000.0),
                                start_worker=False)
    # Warm the sync path once so the fleet latency/stage histogram
    # children exist for the witness to wrap, then re-base the books so
    # the exact-accounting assertions below stay exact.
    disp.infer_one(_frame(-1.0), scene="warm", timeout=60.0)
    disp.reset_stats()
    witness = LockWitness().attach_fleet(disp=disp)
    disp.start()
    n_callers, n_each = 3, 40
    errors: list[Exception] = []
    done = threading.Event()

    def caller(tid):
        try:
            for i in range(n_each):
                out = disp.infer_one(_frame(tid * 1000 + i),
                                     scene=f"s{tid}", timeout=60.0)
                assert float(out["echo"][0]) == tid * 1000 + i
        except Exception as e:  # noqa: BLE001 — surfaced in main thread
            errors.append(e)

    def reader():
        try:
            while not done.is_set():
                snap = disp.obs.snapshot()
                t = snap["collectors"]["serve_slo_totals"]
                total = (t["served"] + t["shed"] + t["expired"]
                         + t["degraded"] + t["failed"] + t["pending"])
                assert total == t["offered"], t
                assert "# TYPE" in render_prometheus(snap)
                json.dumps(snap)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    callers = [threading.Thread(target=caller, args=(t,))
               for t in range(n_callers)]
    readers = [threading.Thread(target=reader) for _ in range(2)]
    for t in callers + readers:
        t.start()
    for t in callers:
        t.join(60)
    done.set()
    for t in readers:
        t.join(10)
    disp.close()
    assert errors == [], errors
    t = disp.slo_totals()
    assert t["served"] == n_callers * n_each == t["offered"]
    # graft-audit v3: observed acquisition edges ⊆ committed order, and
    # the publish-under-dispatch-lock edge was actually exercised.
    committed = load_graph(
        _pathlib.Path(__file__).resolve().parent.parent / LOCK_GRAPH_NAME
    )
    assert committed is not None, "no committed .lock_graph.json"
    witness.assert_subgraph(committed)
    assert any(src == "MicroBatchDispatcher._lock"
               for (src, _dst) in witness.edges())
    assert witness.hold_summary()["MicroBatchDispatcher._lock"]["count"] > 0


def test_snapshot_and_admission_never_block_on_wedged_dispatch():
    """A wedged in-flight dispatch (the observed relay-stall mode) must
    not make observability part of the outage: snapshot/export return
    promptly and submits still admit while the worker is stuck."""
    inj = FaultInjector(_echo)
    release = threading.Event()
    slo = SLOPolicy(deadline_ms=60_000.0, watchdog_ms=60_000.0)
    disp = MicroBatchDispatcher(inj, CFG, slo=slo, trace=True)
    try:
        inj.stall_once(release)
        wedged = disp.submit(_frame(1.0), scene="bad")
        deadline = time.time() + 10
        while disp.slo_totals()["pending"] < 1 and time.time() < deadline:
            time.sleep(0.01)
        t0 = time.perf_counter()
        snap = disp.obs.snapshot()
        dt_snap = time.perf_counter() - t0
        assert dt_snap < 2.0, dt_snap
        t = snap["collectors"]["serve_slo_totals"]
        assert t["pending"] >= 1 and t["offered"] >= 1
        t0 = time.perf_counter()
        queued = disp.submit(_frame(2.0), scene="good")
        assert time.perf_counter() - t0 < 0.5  # admission not blocked
        release.set()
        queued.get(60.0)
        wedged.get(60.0)
    finally:
        release.set()
        disp.close()
