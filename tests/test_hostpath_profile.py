"""tools/hostpath_profile.py unit tests (ISSUE 17).

The profiler's aggregation is pure host code designed for unit testing:
``stage_table`` and ``host_overhead_summary`` get exact-value pins here;
the end-to-end path (fixture build, traced dispatcher, capacity protocol)
is exercised by ``bench.py hostpath`` and its bench-guard contract tests —
not re-run here (it costs ~a minute of real serving).
"""

import importlib.util
import pathlib

REPO = pathlib.Path(__file__).resolve().parent.parent


def _profiler():
    spec = importlib.util.spec_from_file_location(
        "hostpath_profile", REPO / "tools" / "hostpath_profile.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_stage_table_exact_aggregation():
    prof = _profiler()
    durs = [
        {"staged": 1e-3, "device": 3e-3},
        {"staged": 2e-3, "device": 1e-3},
        {"staged": 3e-3, "device": 2e-3},
    ]
    t = prof.stage_table(durs)
    assert set(t) == {"staged", "device"}
    s = t["staged"]
    assert s["count"] == 3
    assert s["mean_ms"] == 2.0
    assert s["p50_ms"] == 2.0
    assert s["p99_ms"] == 3.0  # nearest-rank over 3 samples
    # Shares are of the SUMMED wall and cover it exactly.
    assert s["share"] == 0.5
    assert t["device"]["share"] == 0.5
    assert abs(sum(x["share"] for x in t.values()) - 1.0) < 1e-9


def test_stage_table_handles_missing_stages_per_request():
    prof = _profiler()
    # A shed request never reaches "device": rows aggregate per stage, so
    # counts can differ per stage without corrupting shares.
    t = prof.stage_table([
        {"staged": 1e-3, "device": 1e-3},
        {"staged": 1e-3},
    ])
    assert t["staged"]["count"] == 2
    assert t["device"]["count"] == 1
    assert abs(sum(x["share"] for x in t.values()) - 1.0) < 1e-9


def test_host_overhead_summary_splits_device_out():
    prof = _profiler()
    out = prof.host_overhead_summary([
        {"staged": 2e-3, "device": 1e-3, "sliced": 1e-3},
        {"staged": 4e-3, "device": 3e-3, "sliced": 2e-3},
    ])
    assert out["host_ms_per_request_mean"] == 4.5
    assert out["device_ms_per_request_mean"] == 2.0
    assert out["host_share"] == round(9.0 / 13.0, 4)


def test_profiler_operating_point_matches_fleet_bench():
    """The capacity gate only means something if the profiler measures at
    the EXACT committed-fleet-bench operating point."""
    import bench

    prof = _profiler()
    assert (prof.HW, prof.M, prof.N_HYPS, prof.FRAME_BUCKET) == (
        bench.FLEET_HW, bench.FLEET_M, bench.FLEET_HYPS, bench.FLEET_BUCKET)
