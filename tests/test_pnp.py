"""Tests for the differentiable minimal PnP solver and GN refinement."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from esac_tpu.geometry import (
    so3_log,
    pose_errors,
    project,
    refine_pose_gn,
    rodrigues,
    solve_pnp_minimal,
    transform_points,
)

F = jnp.float32(525.0)
C = jnp.array([320.0, 240.0])


def make_problem(key, n_points=4, noise_px=0.0, spread=1.5):
    """Random scene points + pose, exact (or noisy) pixel observations."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    rvec = jax.random.uniform(k1, (3,), minval=-0.5, maxval=0.5)
    t = jnp.array([0.2, -0.1, 0.3]) + jax.random.uniform(k2, (3,), minval=-0.2, maxval=0.2)
    X = jax.random.uniform(k3, (n_points, 3), minval=-spread, maxval=spread) + jnp.array(
        [0.0, 0.0, 4.0]
    )
    R = rodrigues(rvec)
    x2d = project(transform_points(R, t, X), F, C)
    x2d = x2d + noise_px * jax.random.normal(k4, x2d.shape)
    return rvec, t, X, x2d


@pytest.mark.parametrize("seed", range(8))
def test_minimal_solve_recovers_pose(seed):
    rvec, t, X, x2d = make_problem(jax.random.key(seed))
    rv, tv = solve_pnp_minimal(X, x2d, F, C)
    r_err, t_err = pose_errors(rodrigues(rv), tv, rodrigues(rvec), t)
    assert r_err < 0.5, f"rot err {r_err} deg"
    assert t_err < 0.02, f"trans err {t_err} m"


def test_minimal_solve_vmaps():
    keys = jax.random.split(jax.random.key(42), 64)
    problems = [make_problem(k) for k in keys]
    X = jnp.stack([p[2] for p in problems])
    x2d = jnp.stack([p[3] for p in problems])
    solve = jax.jit(jax.vmap(lambda Xi, xi: solve_pnp_minimal(Xi, xi, F, C)))
    rv, tv = solve(X, x2d)
    assert rv.shape == (64, 3) and tv.shape == (64, 3)
    n_good = 0
    for i, (rvec, t, _, _) in enumerate(problems):
        r_err, t_err = pose_errors(rodrigues(rv[i]), tv[i], rodrigues(rvec), t)
        if r_err < 1.0 and t_err < 0.05:
            n_good += 1
    # Random 4-point geometry occasionally hits a P3P-ambiguous / degenerate
    # configuration; RANSAC tolerates those. Demand a high success rate.
    assert n_good >= 56, f"only {n_good}/64 minimal solves succeeded"


def test_degenerate_sample_is_finite():
    # All four scene points identical: hopeless, but must not NaN.
    X = jnp.tile(jnp.array([[0.0, 0.0, 4.0]]), (4, 1))
    x2d = jnp.tile(C[None], (4, 1))
    rv, tv = solve_pnp_minimal(X, x2d, F, C)
    assert jnp.all(jnp.isfinite(rv)) and jnp.all(jnp.isfinite(tv))


def test_refine_improves_noisy_estimate():
    rvec, t, X, x2d = make_problem(jax.random.key(7), n_points=60, noise_px=0.0)
    # Perturb the pose and refine on many points.
    rv0 = rvec + 0.05
    tv0 = t + jnp.array([0.05, -0.03, 0.04])
    rv, tv = refine_pose_gn(rv0, tv0, X, x2d, F, C, iters=8)
    r_err, t_err = pose_errors(rodrigues(rv), tv, rodrigues(rvec), t)
    r_err0, t_err0 = pose_errors(rodrigues(rv0), tv0, rodrigues(rvec), t)
    assert r_err < 0.1 and t_err < 0.005
    assert r_err < r_err0 and t_err < t_err0


def test_refine_weighted_ignores_outliers():
    rvec, t, X, x2d = make_problem(jax.random.key(9), n_points=80)
    # Corrupt 20 observations badly, weight them ~0.
    x2d = x2d.at[:20].add(300.0)
    w = jnp.concatenate([jnp.zeros(20), jnp.ones(60)])
    rv, tv = refine_pose_gn(rvec + 0.03, t + 0.03, X, x2d, F, C, weights=w, iters=8)
    r_err, t_err = pose_errors(rodrigues(rv), tv, rodrigues(rvec), t)
    assert r_err < 0.1 and t_err < 0.01


def test_solver_is_differentiable():
    rvec, t, X, x2d = make_problem(jax.random.key(11))

    def loss(X_in):
        rv, tv = solve_pnp_minimal(X_in, x2d, F, C)
        return jnp.sum(rv**2) + jnp.sum(tv**2)

    g = jax.grad(loss)(X)
    assert g.shape == X.shape
    assert jnp.all(jnp.isfinite(g))
    assert jnp.any(jnp.abs(g) > 0)


def test_refine_gradient_matches_finite_differences():
    """jax.grad through GN refinement vs numerical gradient (SURVEY.md §4)."""
    rvec, t, X, x2d = make_problem(jax.random.key(13), n_points=12)

    def loss(X_in):
        rv, tv = refine_pose_gn(rvec + 0.02, t + 0.02, X_in, x2d, F, C, iters=4)
        return jnp.sum(rv) + jnp.sum(tv)

    g = jax.grad(loss)(X)
    eps = 1e-3
    for idx in [(0, 0), (3, 2), (7, 1)]:
        Xp = X.at[idx].add(eps)
        Xm = X.at[idx].add(-eps)
        fd = (loss(Xp) - loss(Xm)) / (2 * eps)
        np.testing.assert_allclose(g[idx], fd, rtol=0.05, atol=1e-4)


def test_degenerate_sample_gradient_is_finite():
    """One degenerate minimal sample must not NaN a vmapped batch gradient."""
    X_deg = jnp.tile(jnp.array([[0.0, 0.0, 4.0]]), (4, 1))
    x_deg = jnp.tile(C[None], (4, 1))
    _, _, X_ok, x_ok = make_problem(jax.random.key(20))
    Xb = jnp.stack([X_deg, X_ok])
    xb = jnp.stack([x_deg, x_ok])

    def loss(Xin):
        rv, tv = jax.vmap(lambda a, b: solve_pnp_minimal(a, b, F, C))(Xin, xb)
        return jnp.sum(rv) + jnp.sum(tv)

    g = jax.grad(loss)(Xb)
    assert jnp.all(jnp.isfinite(g)), g


def test_bearings_normalization_grad_finite_at_degenerate_input():
    """Regression for the raw jnp.linalg.norm ray normalization in
    bearings() (graft-lint R2): gradients must stay finite at degenerate
    inputs, per the CLAUDE.md finite-garbage-plus-penalty convention.

    Two layers: (a) bearings() itself at the principal point (xy == 0
    exactly — the degenerate pinhole-center ray); (b) the safe_norm
    normalization at a true all-zero ray, which is exactly the input where
    the old raw-norm VJP returned NaN (0/0 in the norm backward)."""
    from esac_tpu.geometry.pnp import bearings
    from esac_tpu.utils.num import safe_norm

    x2d = jnp.tile(C[None], (4, 1))  # every pixel at the principal point
    g = jax.grad(lambda p: jnp.sum(bearings(p, F, C)))(x2d)
    assert jnp.all(jnp.isfinite(g)), g

    zero_rays = jnp.zeros((4, 3))    # the zero ray a raw norm NaNs on
    g2 = jax.grad(lambda r: jnp.sum(r / safe_norm(r)[..., None]))(zero_rays)
    assert jnp.all(jnp.isfinite(g2)), g2


def test_so3_log_gradient_at_identity():
    g = jax.grad(lambda R: jnp.sum(so3_log(R)))(jnp.eye(3))
    assert jnp.all(jnp.isfinite(g))


def test_gn_step_matches_jacfwd_step():
    """The hand-derived left-perturbation Jacobian in _gn_pose_step must
    produce the same LM step as an autodiff (jacfwd) reference build of the
    same normal equations — a wrong-but-convergent Jacobian would otherwise
    pass every convergence test."""
    from esac_tpu.geometry.pnp import MIN_DEPTH, _gn_pose_step, _solve6_spd

    rvec, t, X, x2d = make_problem(jax.random.key(30), n_points=24, noise_px=1.0)
    R0 = rodrigues(rvec + 0.04)
    t0 = t + jnp.array([0.03, -0.02, 0.05])
    w = jax.random.uniform(jax.random.key(31), (24,), minval=0.2, maxval=1.0)
    damping = 1e-4

    R1, t1 = _gn_pose_step(R0, t0, X, x2d, F, C, w, damping)

    # Reference: residuals r(delta, dt) = proj(exp(delta) R0 X + t0 + dt) - x2d
    def residuals(p):
        Rp = rodrigues(p[:3]) @ R0
        Y = X @ Rp.T + t0 + p[3:]
        z = jnp.maximum(Y[:, 2:3], MIN_DEPTH)
        xp = Y[:, :2] / z * F + C
        return (xp - x2d).reshape(-1)

    J = jax.jacfwd(residuals)(jnp.zeros(6))  # (2N, 6)
    r = residuals(jnp.zeros(6))
    w2 = jnp.repeat(w, 2)
    A = J.T @ (J * w2[:, None])
    g = (J * w2[:, None]).T @ r
    mu = damping * (jnp.trace(A) / 6.0 + 1e-6)
    delta = _solve6_spd(A + mu * jnp.eye(6), g)
    R_ref = rodrigues(-delta[:3]) @ R0
    t_ref = t0 - delta[3:]

    np.testing.assert_allclose(np.asarray(R1), np.asarray(R_ref), atol=2e-5)
    np.testing.assert_allclose(np.asarray(t1), np.asarray(t_ref), atol=2e-4)
