"""graft-audit v4 tests: the R14/R15 grad-safety dataflow pass (golden
trigger + near-miss fixture matrix), the J5 backward-jaxpr hazard census
(unit + diff-gate + CLI end-to-end), the committed degenerate-input corpus
(round-trip + committed-equals-default pin), and the runtime gradient
witness (every grad-registered entry all-finite on the full corpus, plus
the planted-NaN fixture proving the witness CATCHES a violation).

Fixture sources are written into tmp_path trees mimicking the repo layout
(the pass is path-scoped), never into the repo.  The witness sweep runs
ONCE per module (``gradcheck_verdicts``) — each witness compiles one
program and replays every corpus case through it.
"""

from __future__ import annotations

import json
import pathlib
import textwrap

import pytest

from esac_tpu.lint.gradsafety import grad_pass_needed, run_gradsafety_rules

REPO = pathlib.Path(__file__).resolve().parent.parent


def _write(root: pathlib.Path, rel: str, text: str) -> str:
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(text))
    return rel


def _rules(findings) -> list[str]:
    return sorted(f.rule for f in findings)


# --------------------------------------------------------------------------
# R14: unguarded domain-edge primitives in differentiated scope

def test_r14_unguarded_division_golden_and_eps_near_miss(tmp_path):
    _write(tmp_path, "esac_tpu/geometry/bad_div.py", """\
        import jax
        import jax.numpy as jnp

        def loss(x, d):
            return jnp.sum(x / d)          # eps-free denominator

        g = jax.grad(loss)
        """)
    _write(tmp_path, "esac_tpu/geometry/good_div.py", """\
        import jax
        import jax.numpy as jnp

        def loss(x, d):
            return jnp.sum(x / (d + 1e-9))           # eps-dominated
        def loss2(x, d):
            return jnp.sum(x / jnp.maximum(d, 1e-9))  # constant floor
        def loss3(x, d):
            return jnp.sum(x / 3.0)                   # constant
        g = jax.grad(loss)
        g2 = jax.grad(loss2)
        g3 = jax.grad(loss3)
        """)
    findings = run_gradsafety_rules(tmp_path)
    assert _rules(findings) == ["R14"]
    assert findings[0].path == "esac_tpu/geometry/bad_div.py"
    assert "denominator" in findings[0].message


def test_r14_arccos_golden_and_clamp_near_miss(tmp_path):
    _write(tmp_path, "esac_tpu/geometry/angles.py", """\
        import jax
        import jax.numpy as jnp

        def bad_angle(ca):
            return jnp.arccos(ca)                       # unclamped

        def good_angle(ca):
            return jnp.arccos(jnp.clip(ca, -1.0, 1.0))  # clamp dominates

        def bounded_angle(t):
            return jnp.arccos(jnp.cos(t))               # bounded producer

        g = jax.grad(bad_angle)
        g2 = jax.grad(good_angle)
        g3 = jax.grad(bounded_angle)
        """)
    findings = run_gradsafety_rules(tmp_path)
    assert _rules(findings) == ["R14"]
    assert "arccos" in findings[0].message
    assert "clamp" in findings[0].message


def test_r14_half_sandwich_and_wide_clip_do_not_silence_arccos(tmp_path):
    """Review regression: a floor-only maximum or an out-of-range clip is
    NOT a [-1,1] clamp — the fp-noise case (a unit-vector dot product
    marginally above 1) still NaNs, so these must keep flagging; only a
    full in-range sandwich is a near-miss."""
    _write(tmp_path, "esac_tpu/geometry/half_clamp.py", """\
        import jax
        import jax.numpy as jnp

        def floor_only(ca):
            return jnp.arccos(jnp.maximum(ca, -1.0))    # unbounded above

        def wide_clip(ca):
            return jnp.arccos(jnp.clip(ca, -2.0, 2.0))  # bounds outside

        def full_sandwich(ca):
            return jnp.arccos(jnp.minimum(jnp.maximum(ca, -1.0), 1.0))

        g = jax.grad(floor_only)
        g2 = jax.grad(wide_clip)
        g3 = jax.grad(full_sandwich)
        """)
    findings = run_gradsafety_rules(tmp_path)
    assert _rules(findings) == ["R14", "R14"]
    assert all("arccos" in f.message for f in findings)
    texts = " ".join(f.text for f in findings)
    assert "maximum(ca, -1.0)" in texts and "clip(ca, -2.0, 2.0)" in texts


def test_r14_log_and_fractional_pow(tmp_path):
    _write(tmp_path, "esac_tpu/ransac/logs.py", """\
        import jax
        import jax.numpy as jnp

        def bad(p, x):
            return jnp.sum(jnp.log(p)) + jnp.sum(x ** 1.5)

        def good(p, x):
            return (jnp.sum(jnp.log(p + 1e-12))   # eps-dominated log
                    + jnp.sum(jnp.log1p(p))       # log1p is total at 0
                    + jnp.sum(x ** 2)             # integer power is total
                    + jnp.sum((x + 1e-9) ** 0.5)) # eps-dominated base

        g = jax.grad(bad)
        g2 = jax.grad(good)
        """)
    findings = run_gradsafety_rules(tmp_path)
    assert _rules(findings) == ["R14", "R14"]
    assert any("log" in f.message for f in findings)
    assert any("power" in f.message for f in findings)


# --------------------------------------------------------------------------
# R15: the where-VJP trap

def test_r15_where_wrapped_hazard_golden(tmp_path):
    # The documented trap byte-for-byte: the forward NaN is masked, the
    # untaken branch's VJP still runs.
    _write(tmp_path, "esac_tpu/geometry/trap.py", """\
        import jax
        import jax.numpy as jnp

        def loss(x, d):
            safe = jnp.where(d > 0, x / d, 0.0)
            return jnp.sum(safe)

        g = jax.grad(loss)
        """)
    findings = run_gradsafety_rules(tmp_path)
    assert _rules(findings) == ["R15"]
    assert "untaken branch" in findings[0].message


def test_r15_guarded_operand_is_the_sanctioned_near_miss(tmp_path):
    # Both sanctioned spellings: guard the OPERAND with a select-clamp, or
    # keep the in-branch hazard itself eps-dominated (the quartic.py
    # `where(deg, 0, -P / (3 * where(deg, 1, U)))` idiom).
    _write(tmp_path, "esac_tpu/geometry/sanctioned.py", """\
        import jax
        import jax.numpy as jnp

        def loss(x, d):
            return jnp.sum(x / jnp.where(d > 0, d, 1.0))

        def loss2(x, d):
            y = jnp.where(d > 0, x / (d + 1e-9), 0.0)
            return jnp.sum(y)

        def loss3(x, d):
            d_safe = jnp.where(jnp.abs(d) < 1e-9, 1e-9, d)
            return jnp.sum(jnp.where(jnp.abs(d) < 1e-9, 0.0, x / d_safe))

        g = jax.grad(loss)
        g2 = jax.grad(loss2)
        g3 = jax.grad(loss3)
        """)
    assert run_gradsafety_rules(tmp_path) == []


# --------------------------------------------------------------------------
# propagation: helpers, closures, reachability

def test_helper_propagation_guard_and_hazard(tmp_path):
    # lead_safe-style helper: its select-clamp return GUARDS call sites;
    # a hazard inside a reachable helper is flagged IN the helper.
    _write(tmp_path, "esac_tpu/geometry/helpers.py", """\
        import jax
        import jax.numpy as jnp

        def lead_safe(q):
            return jnp.where(jnp.abs(q) < 1e-2, 1e-2, q)

        def _hazard_helper(x, d):
            return x / d                   # reachable via loss2 -> R14 here

        def loss(c, q):
            return jnp.sum(c / lead_safe(q))   # guarded via the helper

        def loss2(x, d):
            return jnp.sum(_hazard_helper(x, d))

        g = jax.grad(loss)
        g2 = jax.grad(loss2)
        """)
    findings = run_gradsafety_rules(tmp_path)
    assert _rules(findings) == ["R14"]
    assert "_hazard_helper" in findings[0].text or "x / d" in findings[0].text


def test_closure_and_lambda_hazards_are_differentiated_scope(tmp_path):
    _write(tmp_path, "esac_tpu/ransac/closures.py", """\
        import jax
        import jax.numpy as jnp

        def loss(xs, d):
            def per_item(x):
                return x / d               # closure inside a grad root
            return jnp.sum(jax.vmap(per_item)(xs))

        g = jax.grad(loss)
        """)
    findings = run_gradsafety_rules(tmp_path)
    assert _rules(findings) == ["R14"]


def test_reachability_and_scope_limits(tmp_path):
    # The same hazard OUTSIDE differentiated reach (never fed to a grad
    # wrapper) and OUTSIDE the geometry/ransac/train scope is not flagged.
    _write(tmp_path, "esac_tpu/geometry/unreached.py", """\
        import jax.numpy as jnp

        def forward_only(x, d):
            return jnp.sum(x / d)          # nothing differentiates this
        """)
    _write(tmp_path, "esac_tpu/models/out_of_scope.py", """\
        import jax
        import jax.numpy as jnp

        def loss(x, d):
            return jnp.sum(x / d)

        g = jax.grad(loss)
        """)
    assert run_gradsafety_rules(tmp_path) == []


def test_custom_vjp_pair_is_differentiated_scope(tmp_path):
    # The defvjp-registered backward IS backward-pass code: hazards there
    # are exactly the NaNs the convention exists to prevent.
    _write(tmp_path, "esac_tpu/ransac/cvjp.py", """\
        import jax
        import jax.numpy as jnp

        @jax.custom_vjp
        def op(x, d):
            return x

        def op_fwd(x, d):
            return x, (x, d)

        def op_bwd(res, g):
            x, d = res
            return (g / d, g)              # hazard in the backward

        op.defvjp(op_fwd, op_bwd)
        """)
    findings = run_gradsafety_rules(tmp_path)
    assert _rules(findings) == ["R14"]
    assert findings[0].text == "return (g / d, g)              # hazard in the backward"


def test_int_annotated_param_denominator_is_static(tmp_path):
    # An int-annotated parameter is a static jit argument: no VJP exists,
    # and division by it is compile-time — the subsample_cells idiom.
    _write(tmp_path, "esac_tpu/ransac/static_denom.py", """\
        import jax
        import jax.numpy as jnp

        def loss(x, n_sub: int, scale: float = 4.0):
            return jnp.sum(x) / n_sub + jnp.sum(x / scale)

        g = jax.grad(loss)
        """)
    assert run_gradsafety_rules(tmp_path) == []


# --------------------------------------------------------------------------
# suppressions, --changed, CLI contract

def test_inline_suppression_silences_r14(tmp_path):
    _write(tmp_path, "esac_tpu/geometry/sup.py", """\
        import jax
        import jax.numpy as jnp

        def loss(x, f):
            return jnp.sum(x / f)  # graft-lint: disable=R14(fixture: focal bounded by construction)

        g = jax.grad(loss)
        """)
    assert run_gradsafety_rules(tmp_path) == []


def test_stale_r14_suppression_reports_on_full_runs(tmp_path, capsys):
    from esac_tpu.lint.cli import main as lint_main

    _write(tmp_path, "esac_tpu/geometry/stale.py", """\
        import jax
        import jax.numpy as jnp

        def loss(x, d):
            return jnp.sum(x / (d + 1e-9))  # graft-lint: disable=R14(masks nothing: already eps-guarded)

        g = jax.grad(loss)
        """)
    rc = lint_main(["--root", str(tmp_path), "--no-jaxpr"])
    err = capsys.readouterr().err
    assert rc == 0
    assert "stale inline suppression (R14" in err


def test_changed_mode_grad_pass_rides_scope_and_lint_edits():
    """--changed skips the grad-safety pass unless a geometry/ransac/train
    or lint file changed — the lock-pass/jaxpr-skip logic mirrored."""
    assert grad_pass_needed(None)
    assert grad_pass_needed(["esac_tpu/geometry/pnp.py"])
    assert grad_pass_needed(["esac_tpu/ransac/kernel.py"])
    assert grad_pass_needed(["esac_tpu/train/e2e.py"])
    assert grad_pass_needed(["esac_tpu/lint/gradsafety.py"])
    assert not grad_pass_needed(
        ["esac_tpu/serve/slo.py", "bench.py", "DESIGN.md",
         "esac_tpu/obs/metrics.py"]
    )


def test_cli_json_format_and_exit_code_for_r14_r15(tmp_path, capsys):
    """Driver contract: R14/R15 ride --format json with the same stable
    line-number-independent ids + per-duplicate ordinals as every rule."""
    from esac_tpu.lint.cli import main as lint_main

    _write(tmp_path, "esac_tpu/geometry/two.py", """\
        import jax
        import jax.numpy as jnp

        def loss(x, d):
            a = jnp.sum(x / d)
            b = jnp.where(d > 0, x / d, 0.0)
            return a + jnp.sum(b)

        g = jax.grad(loss)
        """)
    rc = lint_main(["--root", str(tmp_path), "--no-jaxpr",
                    "--format", "json"])
    captured = capsys.readouterr()
    assert rc == 1
    objs = [json.loads(l) for l in captured.out.strip().splitlines()]
    assert sorted(o["rule"] for o in objs) == ["R14", "R15"]
    for o in objs:
        assert o["id"].startswith(o["rule"] + "-")
    # Ids survive edits above the finding (line-number independence).
    p = tmp_path / "esac_tpu/geometry/two.py"
    p.write_text("# shifted\n" + p.read_text())
    lint_main(["--root", str(tmp_path), "--no-jaxpr", "--format", "json"])
    objs2 = [json.loads(l) for l in
             capsys.readouterr().out.strip().splitlines()]
    assert sorted(o["id"] for o in objs) == sorted(o["id"] for o in objs2)


def test_list_rules_carries_r14_r15_j5(capsys):
    from esac_tpu.lint.cli import main as lint_main

    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("R14:", "R15:", "J5:"):
        assert rule in out


# --------------------------------------------------------------------------
# the repo verdict: clean, with the sanctioned idioms pinned as near-misses

def test_repo_gradsafety_is_clean_and_focal_suppression_is_live():
    """The first-full-tree-run verdict, regression-locked: zero R14/R15
    findings over the committed tree, with the ONE reviewed suppression
    (the focal-length division in geometry/pnp.py bearings) actually
    firing — cleanliness is asserted, not assumed."""
    from esac_tpu.lint.suppress import record_usage

    with record_usage() as used:
        findings = run_gradsafety_rules(REPO)
    assert findings == [], "\n".join(f.format() for f in findings)
    r14_used = {(p, r) for p, _ln, r in used if r == "R14"}
    assert r14_used == {("esac_tpu/geometry/pnp.py", "R14")}, (
        "the bearings focal-division suppression must be the one and only "
        f"live R14 directive; saw {sorted(used)}"
    )


def test_repo_sanctioned_idioms_are_reachable_near_misses():
    """The clean verdict is meaningful only if the analysis actually
    VISITED the sanctioned idioms: the quartic select-clamped divisions,
    so3_log's guarded branches and the GN pivot clamp must all be inside
    the reachable differentiated scope."""
    import ast

    from esac_tpu.lint.ast_rules import _Module, iter_python_files
    from esac_tpu.lint.gradsafety import (
        GRAD_SCOPE_PREFIXES,
        _reachable_functions,
        _registry_grad_roots,
    )

    modules = {}
    for rel in iter_python_files(REPO):
        if not rel.startswith(GRAD_SCOPE_PREFIXES):
            continue
        src = (REPO / rel).read_text()
        m = _Module(rel, ast.parse(src), src.splitlines())
        modules[m.dotted] = m
    reachable = _reachable_functions(REPO, modules)
    for key in [
        ("esac_tpu.geometry.quartic", "_ferrari"),
        ("esac_tpu.geometry.quartic", "solve_quartic"),
        ("esac_tpu.geometry.quartic", "_cbrt"),
        ("esac_tpu.geometry.rotations", "so3_log"),
        ("esac_tpu.geometry.rotations", "rodrigues"),
        ("esac_tpu.geometry.pnp", "_solve6_spd"),
        ("esac_tpu.geometry.pnp", "_p3p_depths"),
        ("esac_tpu.geometry.camera", "reprojection_errors"),
    ]:
        assert key in reachable, f"{key} escaped differentiated scope"
    # And the registry-parsed roots stay in sync with the audited set.
    roots = _registry_grad_roots(REPO, modules)
    assert ("esac_tpu.geometry.pnp", "solve_pnp_minimal") in roots
    assert ("esac_tpu.ransac.refine", "refine_soft_inliers") in roots
    assert ("esac_tpu.ransac.kernel", "dsac_train_loss") in roots
    assert ("esac_tpu.ransac.esac", "esac_train_loss") in roots


# --------------------------------------------------------------------------
# J5: the backward-jaxpr hazard census

def _census_of(fn, *args):
    import jax

    from esac_tpu.lint.ledger import grad_hazard_census

    return grad_hazard_census(jax.make_jaxpr(fn)(*args))


def test_census_counts_unguarded_vs_eps_guarded_division():
    import jax
    import jax.numpy as jnp

    x = jnp.ones((4,))

    bad = _census_of(jax.grad(lambda d: jnp.sum(1.0 / d)), x)
    assert bad["div"]["unguarded"] >= 1

    good = _census_of(jax.grad(lambda d: jnp.sum(1.0 / (d + 1e-9))), x)
    assert good["div"]["unguarded"] == 0
    assert good["div"]["guarded"] >= 1


def test_census_recognizes_floor_clamp_and_select_guards():
    import jax
    import jax.numpy as jnp

    x = jnp.ones((4,))
    floor = _census_of(
        jax.grad(lambda d: jnp.sum(1.0 / jnp.maximum(d, 1e-6))), x
    )
    assert floor["div"]["unguarded"] == 0
    sel = _census_of(
        jax.grad(lambda d: jnp.sum(1.0 / jnp.where(d > 0, d, 1.0))), x
    )
    assert sel["div"]["unguarded"] == 0


def test_census_tie_count_and_softmax_denominators_are_guarded():
    import jax
    import jax.numpy as jnp

    x = jnp.ones((4,))
    # jnp.max's own VJP divides by the tie count (>= 1 by construction).
    mx = _census_of(jax.grad(lambda v: jnp.max(v)), x)
    assert mx.get("div", {"unguarded": 0})["unguarded"] == 0
    sm = _census_of(jax.grad(lambda v: jax.nn.softmax(v)[0]), x)
    assert sm["div"]["unguarded"] == 0


def test_census_flags_unclamped_domain_edges():
    import jax
    import jax.numpy as jnp

    x = jnp.full((4,), 0.5)
    c = _census_of(
        jax.grad(lambda v: jnp.sum(jnp.arccos(v) + jnp.log(v))), x
    )
    assert c["acos"]["unguarded"] >= 1
    assert c["log"]["unguarded"] >= 1
    clamped = _census_of(
        jax.grad(lambda v: jnp.sum(jnp.log(v + 1e-12))), x
    )
    assert clamped["log"]["unguarded"] == 0


def test_census_acos_edge_is_plus_minus_one_not_zero():
    """Review regression: acos/asin are singular at +-1, so an eps-add or
    a floor — which prove 'nonzero', the WRONG edge — must not count as
    guards; a real in-range clip (lax.clamp) or a bounded producer must."""
    import jax
    import jax.numpy as jnp

    x = jnp.full((4,), 0.5)
    eps_added = _census_of(
        jax.grad(lambda v: jnp.sum(jnp.arccos(v + 1e-9))), x
    )
    assert eps_added["acos"]["unguarded"] >= 1
    floored = _census_of(
        jax.grad(lambda v: jnp.sum(jnp.arccos(jnp.maximum(v, -1.0)))), x
    )
    assert floored["acos"]["unguarded"] >= 1
    clipped = _census_of(
        jax.grad(lambda v: jnp.sum(jnp.arccos(jnp.clip(v, -1.0, 1.0)))), x
    )
    assert clipped["acos"]["unguarded"] == 0
    bounded = _census_of(
        jax.grad(lambda v: jnp.sum(jnp.arccos(jnp.cos(v)))), x
    )
    assert bounded["acos"]["unguarded"] == 0


def _grad_stats(census):
    return {
        "pinned": True, "flops": 10, "peak_intermediate_bytes": 10,
        "dot_general_count": 0, "dot_census": {}, "top_intermediates": [],
        "grad": True, "grad_hazards": census,
    }


def test_j5_diff_new_unguarded_site_fails_improvement_is_stale():
    from esac_tpu.lint.ledger import diff_ledger

    old = {"e": _grad_stats({"div": {"guarded": 5, "unguarded": 1}})}
    # A new unguarded site: fail with a J5 finding.
    worse = {"e": _grad_stats({"div": {"guarded": 5, "unguarded": 2}})}
    findings, _ = diff_ledger(old, worse)
    assert [f.rule for f in findings] == ["J5"]
    assert "unguarded" in findings[0].text
    # An improvement (site guarded): stale, never a failure.
    better = {"e": _grad_stats({"div": {"guarded": 6, "unguarded": 0}})}
    findings, stale = diff_ledger(old, better)
    assert findings == [] and len(stale) == 1
    # Guarded-count drift alone: stale.
    drift = {"e": _grad_stats({"div": {"guarded": 7, "unguarded": 1}})}
    findings, stale = diff_ledger(old, drift)
    assert findings == [] and len(stale) == 1
    # A brand-new hazard PRIM with unguarded sites: fail.
    newprim = {"e": _grad_stats(
        {"div": {"guarded": 5, "unguarded": 1},
         "log": {"guarded": 0, "unguarded": 1}}
    )}
    findings, _ = diff_ledger(old, newprim)
    assert [f.rule for f in findings] == ["J5"]


def test_j5_missing_census_is_a_finding_and_round_trips(tmp_path):
    from esac_tpu.lint.ledger import diff_ledger, load_ledger, write_ledger

    cur = {"e": _grad_stats({"div": {"guarded": 2, "unguarded": 0}})}
    # Committed record predates the census (no grad_hazards): J5 finding.
    old = {"e": {k: v for k, v in cur["e"].items()
                 if k not in ("grad", "grad_hazards")}}
    findings, _ = diff_ledger(old, cur)
    assert [f.rule for f in findings] == ["J5"]
    assert "missing-hazard-census" in findings[0].text
    # Round-trip through the committed file is exact.
    path = tmp_path / "ledger.json"
    write_ledger(path, cur)
    findings, stale = diff_ledger(load_ledger(path), cur)
    assert findings == [] and stale == []


def test_cli_j5_gate_exits_1_on_new_unguarded_site(tmp_path, monkeypatch,
                                                   capsys):
    """End-to-end J5 diff gate: a committed census recording FEWER
    unguarded sites than the tree (i.e. someone added an eps-free
    division to a differentiated entry) fails the CLI with exit 1."""
    import jax
    import jax.numpy as jnp

    import esac_tpu.lint.jaxpr_audit as audit_mod
    from esac_tpu.lint.cli import main as lint_main
    from esac_tpu.lint.ledger import LEDGER_NAME, build_ledger, write_ledger
    from esac_tpu.lint.registry import Entry

    closed = jax.make_jaxpr(jax.grad(lambda d: jnp.sum(1.0 / d)))(
        jnp.ones((4,))
    )
    fake = [(Entry("fixture_grad_entry", pinned=False, grad=True,
                   build=lambda: None), closed)]
    monkeypatch.setattr(audit_mod, "trace_entries",
                        lambda entries=None: fake)
    _write(tmp_path, "esac_tpu/ok.py", "import numpy as np\n")

    current, _ = build_ledger(fake)
    assert current["fixture_grad_entry"]["grad_hazards"]["div"]["unguarded"] > 0
    write_ledger(tmp_path / LEDGER_NAME, current)
    assert lint_main(["--root", str(tmp_path)]) == 0

    doctored = {
        name: {**stats,
               "grad_hazards": {"div": {"guarded": 99, "unguarded": 0}}}
        for name, stats in current.items()
    }
    write_ledger(tmp_path / LEDGER_NAME, doctored)
    rc = lint_main(["--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert " J5 " in out and "unguarded" in out


# --------------------------------------------------------------------------
# the corpus: committed, exact, covering the degeneracy classes

def test_corpus_roundtrip_and_committed_matches_default(tmp_path):
    from esac_tpu.lint.gradcheck import (
        GRAD_CORPUS_NAME,
        default_corpus,
        load_corpus,
        write_corpus,
    )

    path = tmp_path / "corpus.json"
    write_corpus(path)
    assert load_corpus(path) == default_corpus()
    assert load_corpus(tmp_path / "missing.json") is None
    committed = load_corpus(REPO / GRAD_CORPUS_NAME)
    assert committed is not None, "no committed corpus: .grad_corpus.json"
    assert committed == default_corpus(), (
        "committed corpus drifted from gradcheck.default_corpus() — "
        "regenerate via write_corpus and review the diff"
    )


def test_corpus_covers_the_degeneracy_classes():
    from esac_tpu.lint.gradcheck import default_corpus

    cases = default_corpus()["cases"]
    assert set(cases) == {
        "collinear_p3p_triad", "coincident_points", "zero_rays",
        "zero_depth_cells", "identity_rotation", "pi_rotation",
        "tie_scores", "all_dropped_routed",
    }
    assert cases["tie_scores"]["tie_hypotheses"] is True
    assert cases["all_dropped_routed"]["kept"] == [False, False]
    assert cases["pi_rotation"]["rvec"][0] == pytest.approx(3.14159265, 1e-6)
    # Every case shares the witness shapes (one compiled program each).
    for case in cases.values():
        assert len(case["coords"]) == 16 and len(case["pixels"]) == 16


# --------------------------------------------------------------------------
# the runtime witness

@pytest.fixture(scope="module")
def gradcheck_verdicts():
    from esac_tpu.lint.gradcheck import GRAD_CORPUS_NAME, load_corpus, run_gradcheck

    corpus = load_corpus(REPO / GRAD_CORPUS_NAME)
    assert corpus is not None
    return run_gradcheck(corpus)


def test_witness_covers_exactly_the_grad_registered_entries():
    from esac_tpu.lint.gradcheck import WITNESSES
    from esac_tpu.lint.registry import ENTRIES

    grad_entries = {e.name for e in ENTRIES if e.grad}
    witness_names = set(WITNESSES) - {"routed_drop_mask"}
    assert witness_names == grad_entries, (
        "witness set out of sync with grad-registered registry entries: "
        f"missing={grad_entries - witness_names}, "
        f"extra={witness_names - grad_entries}"
    )


def test_every_grad_entry_finite_on_the_full_corpus(gradcheck_verdicts):
    """The acceptance gate: all-finite outputs AND gradients for every
    grad-registered entry on every committed degenerate case — the
    'finite garbage + penalty, never control flow' contract executed."""
    v = gradcheck_verdicts
    violations = [
        (entry, case, rec)
        for entry, cases in v.items() if entry != "clean"
        for case, rec in cases.items()
        if not (rec["outputs_finite"] and rec["grads_finite"])
    ]
    assert v["clean"] and violations == [], violations


def test_verdict_block_shape(gradcheck_verdicts):
    from esac_tpu.lint.gradcheck import WITNESSES, default_corpus

    v = gradcheck_verdicts
    assert set(v) == set(WITNESSES) | {"clean"}
    for entry in WITNESSES:
        assert set(v[entry]) == set(default_corpus()["cases"])
        for rec in v[entry].values():
            assert set(rec) == {"outputs_finite", "grads_finite"}
    # The verdict block is the json-able record the lint publishes.
    json.dumps(v)


def test_planted_nan_is_caught_by_the_witness():
    """The witness must be able to FAIL: a raw-norm loss (the exact
    hazard R2/R14 police) gradchecked on the coincident-points case
    produces a non-finite gradient, and check_case reports it."""
    import jax
    import jax.numpy as jnp

    from esac_tpu.lint.gradcheck import (
        GRAD_CORPUS_NAME,
        _case_arrays,
        check_case,
        load_corpus,
        run_gradcheck,
    )

    jax.config.update("jax_platforms", "cpu")

    def make_planted():
        @jax.jit
        def run(coords, pixels, f, c, rvec, tvec, offs, kept):
            def loss(coords):
                # raw jnp.linalg.norm: NaN VJP at zero difference
                return jnp.sum(jnp.linalg.norm(coords - coords[0], axis=-1))

            val, g = jax.value_and_grad(loss)(coords)
            return {"loss": val}, {"coords": g}

        return run

    corpus = load_corpus(REPO / GRAD_CORPUS_NAME)
    case = corpus["cases"]["coincident_points"]
    v = check_case(make_planted(), _case_arrays(case))
    assert v["outputs_finite"] is True
    assert v["grads_finite"] is False
    # And through the full sweep machinery: the planted witness flips the
    # aggregate verdict to not-clean.
    verdicts = run_gradcheck(corpus, witnesses={"planted": make_planted})
    assert verdicts["clean"] is False
    assert verdicts["planted"]["coincident_points"]["grads_finite"] is False
