"""Unit tests for axis-angle / rotation-matrix math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from esac_tpu.geometry import rodrigues, rot_error_deg, rotation_angle_deg, skew, so3_log


def random_rvecs(key, n, max_angle=np.pi - 0.05):
    k1, k2 = jax.random.split(key)
    axes = jax.random.normal(k1, (n, 3))
    axes = axes / jnp.linalg.norm(axes, axis=-1, keepdims=True)
    angles = jax.random.uniform(k2, (n, 1), minval=1e-4, maxval=max_angle)
    return axes * angles


def test_skew_cross_product():
    a = jnp.array([1.0, 2.0, 3.0])
    b = jnp.array([-0.5, 0.7, 2.0])
    np.testing.assert_allclose(skew(a) @ b, jnp.cross(a, b), atol=1e-6)


def test_rodrigues_is_rotation():
    rvecs = random_rvecs(jax.random.key(0), 64)
    R = rodrigues(rvecs)
    eye = jnp.eye(3)
    np.testing.assert_allclose(R @ jnp.swapaxes(R, -1, -2), jnp.broadcast_to(eye, R.shape), atol=1e-5)
    np.testing.assert_allclose(jnp.linalg.det(R), jnp.ones(64), atol=1e-5)


def test_rodrigues_known_90deg():
    # 90 deg about z: x -> y.
    R = rodrigues(jnp.array([0.0, 0.0, np.pi / 2]))
    np.testing.assert_allclose(R @ jnp.array([1.0, 0.0, 0.0]), jnp.array([0.0, 1.0, 0.0]), atol=1e-6)


def test_rodrigues_small_angle_stable():
    tiny = jnp.array([1e-9, -1e-9, 1e-9])
    R = rodrigues(tiny)
    assert jnp.all(jnp.isfinite(R))
    np.testing.assert_allclose(R, jnp.eye(3), atol=1e-7)
    # Gradient must be finite at ~zero angle too.
    g = jax.grad(lambda r: jnp.sum(rodrigues(r)))(tiny)
    assert jnp.all(jnp.isfinite(g))


def test_log_roundtrip():
    rvecs = random_rvecs(jax.random.key(1), 128)
    back = so3_log(rodrigues(rvecs))
    np.testing.assert_allclose(back, rvecs, atol=1e-3)


def test_log_near_pi():
    rvecs = random_rvecs(jax.random.key(2), 32, max_angle=np.pi - 1e-4)
    # Scale all to an angle of ~pi - 1e-3.
    rvecs = rvecs / jnp.linalg.norm(rvecs, axis=-1, keepdims=True) * (np.pi - 1e-3)
    R = rodrigues(rvecs)
    R2 = rodrigues(so3_log(R))
    np.testing.assert_allclose(rot_error_deg(R, R2), jnp.zeros(32), atol=0.1)


def test_rotation_angle():
    rv = jnp.array([0.0, 0.3, 0.0])
    assert rotation_angle_deg(rodrigues(rv)) == pytest.approx(np.degrees(0.3), abs=1e-3)


def test_rot_error_composition():
    a = jnp.array([0.1, 0.0, 0.0])
    b = jnp.array([0.25, 0.0, 0.0])
    err = rot_error_deg(rodrigues(a), rodrigues(b))
    assert err == pytest.approx(np.degrees(0.15), abs=1e-3)


def test_vmap_jit_compose():
    rvecs = random_rvecs(jax.random.key(3), 16)
    R_vmapped = jax.jit(jax.vmap(rodrigues))(rvecs)
    np.testing.assert_allclose(R_vmapped, rodrigues(rvecs), atol=1e-6)


def test_quaternion_to_matrix_identities():
    from esac_tpu.geometry.rotations import quaternion_to_matrix

    np.testing.assert_allclose(
        quaternion_to_matrix(jnp.array([1.0, 0, 0, 0])), jnp.eye(3), atol=1e-6
    )
    # q and -q encode the same rotation.
    q = jnp.array([0.3, -0.5, 0.2, 0.79])
    np.testing.assert_allclose(
        quaternion_to_matrix(q), quaternion_to_matrix(-q), atol=1e-6
    )
    # Unnormalized input is normalized defensively.
    np.testing.assert_allclose(
        quaternion_to_matrix(3.0 * q), quaternion_to_matrix(q), atol=1e-5
    )
    # Agreement with rodrigues on a known axis-angle.
    import numpy as _np
    angle = 0.8
    axis = jnp.array([0.0, 1.0, 0.0])
    qr = jnp.concatenate([jnp.array([_np.cos(angle / 2)]), _np.sin(angle / 2) * axis])
    np.testing.assert_allclose(
        quaternion_to_matrix(qr), rodrigues(axis * angle), atol=1e-5
    )
