"""Unit tests for the probe's fast-failure self-retry (tools/tpu_probe.py).

The UNAVAILABLE-retry loop re-execs the probe in place (same pid) so the
chip-recovery supervisor's liveness accounting survives; these tests pin the
retry/give-up decision logic without touching any backend.
"""

import importlib.util
import json
import pathlib
import sys

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "tpu_probe", pathlib.Path(__file__).parent.parent / "tools" / "tpu_probe.py"
)
tpu_probe = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(tpu_probe)
# Captured before the autouse fixture zeroes the sleep for the retry tests.
_REAL_RETRY_SLEEP_S = tpu_probe.RETRY_SLEEP_S


@pytest.fixture(autouse=True)
def _isolated(tmp_path, monkeypatch):
    monkeypatch.setattr(tpu_probe, "RESULT", str(tmp_path / "probe.json"))
    monkeypatch.setattr(tpu_probe, "RETRY_SLEEP_S", 0.0)
    yield


def test_retry_reexecs_same_process_with_attempt_bump(monkeypatch):
    calls = {}

    def fake_execve(exe, argv, env):
        calls["exe"], calls["argv"], calls["env"] = exe, argv, env
        raise SystemExit(0)  # execve never returns; emulate by exiting

    monkeypatch.setattr(tpu_probe.os, "execve", fake_execve)
    monkeypatch.setenv("TPU_PROBE_ATTEMPT", "3")
    with pytest.raises(SystemExit):
        tpu_probe._retry_or_give_up(RuntimeError("UNAVAILABLE: setup error"))
    assert calls["exe"] == sys.executable
    assert calls["argv"][1].endswith("tpu_probe.py")
    assert calls["env"]["TPU_PROBE_ATTEMPT"] == "4"
    phase = json.load(open(tpu_probe.RESULT))
    assert phase["phase"] == "retry_unavailable" and phase["attempt"] == 3


def test_gives_up_after_max_attempts(monkeypatch):
    # Stubbed even though the give-up path must not reach it: a regression
    # in the budget check would otherwise REPLACE the pytest process with a
    # real TPU-touching probe (os.execve never returns).
    def exploded(*a):  # pragma: no cover - the test fails if this runs
        raise AssertionError("execve reached on the give-up path")

    monkeypatch.setattr(tpu_probe.os, "execve", exploded)
    monkeypatch.setenv("TPU_PROBE_ATTEMPT", str(tpu_probe.MAX_ATTEMPTS))
    exc = RuntimeError("UNAVAILABLE")
    with pytest.raises(RuntimeError):
        tpu_probe._retry_or_give_up(exc)
    # The phase file records the final attempt (supervisor sees a dead
    # probe + this breadcrumb).
    phase = json.load(open(tpu_probe.RESULT))
    assert phase["attempt"] == tpu_probe.MAX_ATTEMPTS


def test_gives_up_when_wall_clock_budget_spent(monkeypatch):
    """Even with attempts left, a lineage older than MAX_RETRY_WALL_S must
    die rather than overlap chip_recovery.sh's replacement probe."""
    import time

    def exploded(*a):  # pragma: no cover
        raise AssertionError("execve reached past the wall-clock budget")

    monkeypatch.setattr(tpu_probe.os, "execve", exploded)
    monkeypatch.setenv("TPU_PROBE_ATTEMPT", "2")  # far from MAX_ATTEMPTS
    monkeypatch.setenv(
        "TPU_PROBE_T0", str(time.time() - tpu_probe.MAX_RETRY_WALL_S)
    )
    with pytest.raises(RuntimeError):
        tpu_probe._retry_or_give_up(RuntimeError("UNAVAILABLE"))
    phase = json.load(open(tpu_probe.RESULT))
    assert phase["elapsed_s"] >= tpu_probe.MAX_RETRY_WALL_S - 1


def test_retry_budget_fits_supervisor_abandonment_window():
    """The retry lineage's wall-clock ceiling must end before
    chip_recovery.sh's 30-min hung-probe abandonment so a fast-cycling probe
    is never overlapped by a replacement (one watched TPU client at a time).
    The enforced guard is MAX_RETRY_WALL_S (attempt counting alone can't
    bound wall time under CPU contention); keep slack for the attempt in
    flight when the budget check fires."""
    # The budget check gates when the last attempt may START, so the window
    # must absorb that attempt's whole runtime: allow 10 min for a jax
    # import + backend init on a contended 1-core host.
    worst_final_attempt_s = 600.0
    assert (tpu_probe.MAX_RETRY_WALL_S + _REAL_RETRY_SLEEP_S
            + worst_final_attempt_s) <= 1800
    # Attempt cap stays a secondary bound under the same window at the
    # nominal ~15s init cost per attempt.
    assert tpu_probe.MAX_ATTEMPTS * (_REAL_RETRY_SLEEP_S + 15.0) <= 1800
