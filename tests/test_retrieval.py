"""Retrieval front-end tests: image-only requests at the fleet tier
(ISSUE 18, DESIGN.md §22).

The load-bearing claims:

- the scene index enrolls/removes prototypes under a static max-scenes
  axis, typed at its edges (ManifestError), and index mutations NEVER
  recompile the jitted retriever forward (prototypes + mask are traced);
- ``infer_image`` serves a confident query end to end — retrieval
  posterior -> breaker-gated top-K -> routed expert dispatch -> winner
  by soft-inlier score — and its accounting sums exactly to offered;
- misses are TYPED and accounted by class: empty index, low-confidence
  posterior, all-candidates-tripped (the RetrievalMissError family);
- a breaker-tripped top-1 candidate is skipped (never dispatched) and
  the runner-up backfills; ``release_scene`` restores top-1 routing
  BIT-IDENTICALLY to the pre-trip answer;
- every candidate dispatch failing converts to a typed
  RetrievalCandidatesExhaustedError (outcome ``failed``), and the
  observed (error, outcome) pairs stay inside the committed
  ``.fault_taxonomy.json`` edges;
- the posterior-prefetch seam feeds ``WeightPrefetcher.
  observe_candidates`` with mass-weighted candidates and never raises;
- the retrieval locks ride ``LockWitness.attach_fleet`` and the
  observed acquisition order stays inside the committed lock graph.

The fleet here is host-fake (echo-style infer fns, dummy checkpoint
paths — no weights are ever loaded), so nearly the whole file is
tier-1 cheap; one test compiles the REAL tiny retriever to pin the
zero-recompile contract.
"""

import pathlib

import numpy as np
import pytest

from esac_tpu.fleet import FleetPolicy, FleetRouter, Replica
from esac_tpu.ransac import RansacConfig
from esac_tpu.registry import SceneManifest, SceneRegistry
from esac_tpu.registry.manifest import SceneEntry, ScenePreset
from esac_tpu.retrieval import (
    RetrievalCandidatesExhaustedError,
    RetrievalFront,
    RetrievalMissError,
    RetrievalPolicy,
    SceneIndex,
)
from esac_tpu.serve import (
    FaultInjector,
    MicroBatchDispatcher,
    ShedError,
    SLOPolicy,
)
from esac_tpu.serve.slo import ConfigError

CFG = RansacConfig(n_hyps=8, refine_iters=2, frame_buckets=(1,),
                   serve_max_wait_ms=0.0, serve_queue_depth=64)
D = 4                      # fake embedding dim
SCENES = ("a", "b", "c")   # one-hot prototypes along axes 0..2


def _onehot(i):
    v = np.zeros(D, np.float32)
    v[i] = 1.0
    return v

_SCENE_VECS = {sid: _onehot(i) for i, sid in enumerate(SCENES)}


def _query(sid, pure=1.0, other=None):
    """A serve-shaped frame dict: the image leaf carries ``pure`` mass
    on ``sid``'s axis (optionally split with ``other``) — axis 3
    belongs to NO scene (the noise direction)."""
    v = pure * _SCENE_VECS[sid]
    if other is not None:
        v = v + (1.0 - pure) * _SCENE_VECS[other]
    return {"image": v.astype(np.float32)}


def _noise_query():
    v = np.zeros(D, np.float32)
    v[3] = 1.0
    return {"image": v}


def _fake_retriever(params, protos, mask, images):
    """Host mirror of make_retrieval_fn's product: normalized embedding,
    masked cosine posterior at temperature 0.1."""
    x = np.asarray(images, np.float32)
    if x.ndim == 1:
        x = x[None]
    emb = x / np.maximum(
        np.linalg.norm(x, axis=-1, keepdims=True), 1e-12)
    logits = emb @ np.asarray(protos, np.float32).T / 0.1
    logits = np.where(np.asarray(mask)[None, :], logits, -1e30)
    z = logits - logits.max(axis=-1, keepdims=True)
    p = np.exp(z)
    return {"embedding": emb, "posterior": p / p.sum(-1, keepdims=True)}


def _scene_infer(tree, scene=None, route_k=None):
    """Deterministic per-scene expert fake: soft-inlier score is the
    query's alignment with the dispatched scene's axis, so the GT scene
    wins the cross-candidate argmax and reruns are bit-identical."""
    x = np.asarray(tree["image"], np.float32)
    s = x @ _SCENE_VECS[scene]
    return {"scores": s[:, None],
            "rvec": (x * 2.0 + ord(scene[0])).astype(np.float32),
            "expert": np.zeros((x.shape[0],), np.int32)}


def _index(scenes=SCENES, capacity=8):
    idx = SceneIndex(capacity=capacity, embed_dim=D)
    for sid in scenes:
        idx.enroll(sid, _SCENE_VECS[sid][None])
    return idx


def _registry(scenes=SCENES):
    m = SceneManifest()
    preset = ScenePreset(height=16, width=16, num_experts=2, gated=False)
    for sid in scenes:
        m.add(SceneEntry(scene_id=sid, version=1,
                         expert_ckpt=f"/ck_{sid}", preset=preset))
    return SceneRegistry(m)


def _image_fleet(n=2, policy=None, front_policy=None, start=True,
                 with_registry=True, infer=_scene_infer):
    slo = SLOPolicy(watchdog_ms=150.0, watchdog_poll_ms=10.0)
    reps, injs = [], {}
    for i in range(n):
        name = f"r{i}"
        inj = FaultInjector(infer, tag=name)
        disp = MicroBatchDispatcher(inj, CFG, slo=slo,
                                    start_worker=False)
        reps.append(Replica(name, disp,
                            registry=_registry() if with_registry
                            else None))
        injs[name] = inj
    router = FleetRouter(reps, policy or FleetPolicy(poll_ms=2.0),
                         start=False)
    front = RetrievalFront(
        _fake_retriever, None, _index(),
        policy=front_policy or RetrievalPolicy(top_k=2),
    )
    router.attach_retrieval(front)
    if start:
        for rep in reps:
            rep.dispatcher.start()
        router.start()
    return router, front, injs


def _front_consistent(front):
    s = front.stats()
    assert (s["served"] + s["shed"] + s["expired"] + s["failed"]
            + s["degraded"] + s["pending"] == s["offered"]), s
    return s


# ---------------- policy / index edges ----------------

def test_policy_validation():
    with pytest.raises(ValueError):
        RetrievalPolicy(top_k=0)
    with pytest.raises(ValueError):
        RetrievalPolicy(min_confidence=1.5)
    with pytest.raises(ValueError):
        RetrievalPolicy(prefetch_min_p=-0.1)
    with pytest.raises(ValueError):
        # top_k must fit the index's static axis.
        RetrievalFront(_fake_retriever, None,
                       SceneIndex(capacity=1, embed_dim=D),
                       policy=RetrievalPolicy(top_k=2))


def test_index_enroll_remove_typed_and_idempotent():
    from esac_tpu.registry import ManifestError

    idx = SceneIndex(capacity=2, embed_dim=D)
    with pytest.raises(ValueError):
        SceneIndex(capacity=0, embed_dim=D)
    idx.enroll("a", _SCENE_VECS["a"][None])
    with pytest.raises(ManifestError):
        idx.enroll("z", np.zeros((1, D + 1), np.float32))  # dim mismatch
    idx.enroll("b", _SCENE_VECS["b"][None])
    with pytest.raises(ManifestError):
        idx.enroll("c", _SCENE_VECS["c"][None])  # table full
    # Re-enroll refreshes in place (no second slot).
    idx.enroll("a", _SCENE_VECS["a"][None])
    assert len(idx) == 2
    assert idx.remove("a") is True
    assert idx.remove("a") is False  # idempotent
    assert len(idx) == 1
    protos, mask, ids = idx.snapshot()
    assert protos.shape == (2, D) and mask.sum() == 1
    assert "b" in ids and "a" not in ids


def test_real_retriever_no_recompile_across_index_mutations():
    """The zero-recompile contract: prototypes and mask are TRACED
    arguments of the one jitted forward, so enroll/remove/refresh never
    grow the jit cache (one entry per batch shape, ever)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from esac_tpu.retrieval.model import (
        RetrievalConfig,
        build_retriever,
        make_retrieval_fn,
    )

    cfg = RetrievalConfig(height=16, width=16, max_scenes=4, embed_dim=D,
                          channels=(2,))
    fn = make_retrieval_fn(cfg)
    img = np.zeros((1, cfg.height, cfg.width, 3), np.float32)
    params = build_retriever(cfg).init(jax.random.key(0), img)
    idx = SceneIndex(capacity=cfg.max_scenes, embed_dim=cfg.embed_dim)

    def posterior():
        protos, mask, ids = idx.snapshot()
        out = fn(params, protos, mask, img)
        return np.asarray(out["posterior"][0]), ids

    rng = np.random.RandomState(0)
    emb = rng.rand(3, cfg.embed_dim).astype(np.float32)
    idx.enroll("a", emb[:1])
    p, _ = posterior()
    baseline = fn._cache_size()
    idx.enroll("b", emb[1:2])
    idx.enroll("c", emb[2:])
    p, ids = posterior()
    assert fn._cache_size() == baseline, "enroll recompiled the forward"
    # Masked slots carry exactly zero posterior mass.
    empty = [i for i, sid in enumerate(ids) if sid is None]
    assert float(p[empty].sum()) == 0.0
    assert np.isclose(p.sum(), 1.0, atol=1e-5)
    idx.remove("b")
    p, _ = posterior()
    assert fn._cache_size() == baseline, "remove recompiled the forward"


# ---------------- the served path ----------------

def test_image_request_serves_and_accounts_exactly():
    router, front, _ = _image_fleet()
    try:
        out = router.infer_image(_query("a", 0.9, other="b"))
        assert out["retrieval"]["scene"] == "a"
        assert out["retrieval"]["top1"] == "a"
        assert list(out["retrieval"]["candidates"]) == ["a", "b"]
        assert "scores" in out and "rvec" in out
        s = _front_consistent(front)
        assert s["offered"] == s["served"] == 1
        assert s["decided"] == 1 and s["pending"] == 0
        assert s["winners_noted"] == 1 and s["top1_hits"] == 1
        assert s["recall_proxy_top1"] == 1.0
        assert s["candidate_fanout_mean"] == 2.0
        # The per-candidate fleet books ride underneath untouched.
        t = router.fleet_totals()
        assert t["offered"] == 2 and t["served"] == 2
    finally:
        router.close(close_replicas=True)


def test_image_requires_attached_front_and_rejects_double_attach():
    router, front, _ = _image_fleet(start=False)
    try:
        with pytest.raises(ConfigError):
            router.attach_retrieval(front)  # second attach is typed
    finally:
        router.close(close_replicas=True)
    bare = FleetRouter(
        [Replica("r0", MicroBatchDispatcher(
            FaultInjector(_scene_infer, tag="r0"), CFG,
            slo=SLOPolicy(), start_worker=False))],
        FleetPolicy(poll_ms=2.0), start=False,
    )
    try:
        with pytest.raises(ConfigError):
            bare.infer_image(_query("a"))
    finally:
        bare.close(close_replicas=True)


def test_misses_are_typed_shed_and_accounted_by_class():
    router, front, _ = _image_fleet()
    try:
        # Low confidence: the noise axis matches nothing -> uniform
        # posterior 1/3 < min_confidence 0.35.
        with pytest.raises(RetrievalMissError) as ei:
            router.infer_image(_noise_query())
        assert ei.value.retryable is False
        assert isinstance(ei.value, ShedError)
        s = _front_consistent(front)
        assert s["shed"] == 1 and s["missed_low_confidence"] == 1
        assert s["error_types"] == {"RetrievalMissError": 1}
        # No expert dispatch was spent on the miss.
        assert router.fleet_totals()["offered"] == 0
    finally:
        router.close(close_replicas=True)
    # Empty index: typed miss in its own class.
    empty = RetrievalFront(_fake_retriever, None,
                           SceneIndex(capacity=4, embed_dim=D))
    with pytest.raises(RetrievalMissError):
        empty.decide(_query("a"))
    assert empty.stats()["missed_no_candidate"] == 1


# ---------------- breaker gate / release_scene ----------------

def _trip(router, sid, version=1):
    for rep in router._replicas.values():
        reg = rep.registry
        with reg._health_lock:
            reg._tripped[(sid, version)] = "test drill"


def test_breaker_tripped_top1_falls_through_to_runner_up_then_restores():
    import threading

    from esac_tpu.lint.lockgraph import LOCK_GRAPH_NAME, load_graph
    from esac_tpu.lint.witness import LockWitness

    router, front, _ = _image_fleet(start=False)
    witness = LockWitness()
    witness.attach_fleet(router=router)
    for rep in router._replicas.values():
        rep.dispatcher.start()
    router.start()
    try:
        q = _query("a", 0.8, other="b")
        before = router.infer_image(q)
        assert before["retrieval"]["scene"] == "a"
        _trip(router, "a")
        after = router.infer_image(q)
        # Top-1 "a" is SKIPPED (never dispatched); "b" backfills and
        # "c" pads the fan-out back to top_k.
        assert after["retrieval"]["scene"] == "b"
        assert "a" not in after["retrieval"]["candidates"]
        assert list(after["retrieval"]["candidates"]) == ["b", "c"]
        assert after["retrieval"]["top1"] == "a"  # health-agnostic
        s = _front_consistent(front)
        assert s["served"] == 2 and s["tripped_skipped"] == 1
        # Operator release restores top-1 routing bit-identically.
        for rep in router._replicas.values():
            assert rep.registry.release_scene("a") is True
        restored = router.infer_image(q)
        assert restored["retrieval"] == before["retrieval"]
        for key in ("scores", "rvec", "expert"):
            assert np.array_equal(restored[key], before[key]), key
        # All scenes tripped -> typed miss in the tripped class.
        for sid in SCENES:
            _trip(router, sid)
        with pytest.raises(RetrievalMissError):
            router.infer_image(q)
        s = _front_consistent(front)
        assert s["missed_tripped"] == 1 and s["shed"] == 1
        assert s["served"] == 3 and s["pending"] == 0
    finally:
        router.close(close_replicas=True)
    committed = load_graph(
        pathlib.Path(__file__).resolve().parent.parent / LOCK_GRAPH_NAME
    )
    assert committed is not None
    witness.assert_subgraph(committed)
    # LEAF locks never appear in edges (nothing is held across them —
    # that IS the claim); their hold histograms prove they were both
    # witnessed and exercised.
    held = set(witness.hold_summary())
    assert any(n.startswith("RetrievalFront._lock") for n in held), held
    assert any(n.startswith("SceneIndex._lock") for n in held), held
    # And no edge ever NESTS another lock under them.
    for src, _dst in witness.edges():
        assert not src.startswith(("RetrievalFront._lock",
                                   "SceneIndex._lock")), (src, _dst)
    assert threading.active_count() < 50  # no leaked fleet threads


def test_all_candidate_dispatches_failed_raises_exhausted_typed():
    from esac_tpu.lint.witness import OutcomeWitness
    from esac_tpu.registry.health import SceneLoadError

    router, front, injs = _image_fleet()
    ow = OutcomeWitness.from_repo(
        pathlib.Path(__file__).resolve().parent.parent)
    try:
        # Every replica faults every candidate dispatch with a
        # scene-level (non-failover) fault -> admission succeeds, the
        # dispatch dies typed, and the image request converts to
        # RetrievalCandidatesExhaustedError (outcome: failed).
        for inj in injs.values():
            inj.fail_times(SceneLoadError("drill: storage down"),
                           times=8)
        with pytest.raises(RetrievalCandidatesExhaustedError) as ei:
            router.infer_image(_query("a", 0.9, other="b"))
        assert ei.value.retryable is True
        ow.observe(type(ei.value).__name__, "failed")
        s = _front_consistent(front)
        assert s["failed"] == 1 and s["decided"] == 1
        assert s["error_types"] == \
            {"RetrievalCandidatesExhaustedError": 1}
        # The miss edge too: noise query -> (RetrievalMissError, shed).
        with pytest.raises(RetrievalMissError) as ei2:
            router.infer_image(_noise_query())
        ow.observe(type(ei2.value).__name__, "shed")
        ow.assert_consistent()
    finally:
        router.close(close_replicas=True)


# ---------------- the prefetch seam ----------------

def test_posterior_feeds_prefetcher_and_never_raises():
    from esac_tpu.registry.prefetch import WeightPrefetcher

    clock = [0.0]
    pf = WeightPrefetcher(registry=None, clock=lambda: clock[0])
    front = RetrievalFront(_fake_retriever, None, _index(),
                           policy=RetrievalPolicy(top_k=2),
                           prefetch_sinks=(pf.observe_candidates,))
    # Genuinely ambiguous (temperature 0.1 sharpens hard — a 0.55/0.45
    # split keeps the runner-up above the prefetch mass floor).
    decision = front.decide(_query("a", 0.55, other="b"))
    front.feed_prefetch(decision)
    st = pf.stats()
    assert st["posterior_feeds"] == 1
    assert front.stats()["prefetch_feeds"] == 1
    # The ambiguous runner-up rode the feed (mass >= prefetch_min_p);
    # sub-floor scenes did not.
    fed = {s for s, _t, _w in pf._arrivals}
    assert {"a", "b"} <= fed and "c" not in fed
    # A broken sink is counted, never raised through the request path.
    def broken(weights):
        raise RuntimeError("sink down")
    front.add_prefetch_sink(broken)
    front.feed_prefetch(decision)
    assert front.stats()["feed_errors"] == 1
    # Garbage into the arrival seam is swallowed by contract too.
    pf.observe_candidates(None)
    assert pf.stats()["feed_errors"] >= 1


def test_router_wires_replica_prefetchers_as_sinks():
    router, front, _ = _image_fleet(start=False)
    try:
        reg = next(iter(router._replicas.values())).registry
        reg.attach_prefetcher(start=False)
        front2 = RetrievalFront(_fake_retriever, None, _index())
        r2 = FleetRouter(
            [Replica("p0", MicroBatchDispatcher(
                FaultInjector(_scene_infer, tag="p0"), CFG,
                slo=SLOPolicy(), start_worker=False), registry=reg)],
            FleetPolicy(poll_ms=2.0), start=False,
        )
        r2.attach_retrieval(front2)
        try:
            d = front2.decide(_query("a", 0.55, other="b"))
            front2.feed_prefetch(d)
            assert reg._prefetcher.stats()["posterior_feeds"] == 1
        finally:
            r2.close(close_replicas=True)
    finally:
        router.close(close_replicas=True)
