"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding tests run on
XLA's host-platform virtual devices instead (SURVEY.md §4 "Distributed
without a cluster").  Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The container's sitecustomize force-registers the TPU ("axon") backend and
# overrides JAX_PLATFORMS; pin the config after import so tests always run on
# the virtual 8-device CPU mesh.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)


def pytest_report_header(config):
    return f"jax {jax.__version__} devices={jax.device_count()} ({jax.devices()[0].platform})"
