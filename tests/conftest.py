"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding tests run on
XLA's host-platform virtual devices instead (SURVEY.md §4 "Distributed
without a cluster").  Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The container's sitecustomize force-registers the TPU ("axon") backend and
# overrides JAX_PLATFORMS; pin the config after import so tests always run on
# the virtual 8-device CPU mesh.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)


def pytest_report_header(config):
    return f"jax {jax.__version__} devices={jax.device_count()} ({jax.devices()[0].platform})"


# ---- tier-1 wall-clock record (tests/test_tier1_budget.py) ----
#
# The tier-1 gate runs under `timeout -k 10 870` (ROADMAP.md): blowing the
# budget kills the whole suite, so creep toward it must be visible BEFORE it
# fires.  Every tier-1-shaped session (the `-m "not slow"` selection over the
# full tests/ dir) records its wall time; the budget-guard test asserts the
# most recent record stayed inside the budget.

import json  # noqa: E402
import pathlib  # noqa: E402
import time  # noqa: E402

_SESSION_T0: dict = {}
TIER1_WALL_FILE = pathlib.Path(__file__).resolve().parent.parent / ".tier1_wall.json"


def pytest_sessionstart(session):
    _SESSION_T0["t"] = time.time()


def pytest_sessionfinish(session, exitstatus):
    t0 = _SESSION_T0.get("t")
    markexpr = getattr(session.config.option, "markexpr", "") or ""
    # Only full tier-1-shaped runs are meaningful records: the right marker,
    # a full-suite-sized collection (file-picked iteration runs and -k
    # slices must not overwrite the record with a tiny wall time), and a
    # run that actually finished — a Ctrl-C'd session (exitstatus 2+) would
    # record a misleadingly small time and blind the budget guard.
    if (t0 is None or markexpr != "not slow"
            or session.testscollected < 100 or int(exitstatus) > 1):
        return
    try:
        # Merge-write: other recorders (tests/test_lint.py stores the lint
        # gate's own wall clock under "lint_wall_s") share this file —
        # preserve their keys instead of clobbering the record.
        record = {}
        if TIER1_WALL_FILE.exists():
            try:
                record = json.loads(TIER1_WALL_FILE.read_text())
            except (OSError, ValueError):
                record = {}
        record.update({
            "elapsed_s": round(time.time() - t0, 1),
            "t": time.time(),
            "markexpr": markexpr,
            "n_collected": session.testscollected,
        })
        TIER1_WALL_FILE.write_text(json.dumps(record))
    except OSError:
        pass
