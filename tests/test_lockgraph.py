"""graft-audit v3 tests: the R12/R13 fleet concurrency analysis, the
committed lock-graph artifact machinery, and the runtime lock witness.

Golden trigger + near-miss fixtures ride tmp_path trees mimicking the
fleet layout (the pass is scoped to esac_tpu/{serve,registry,obs}/),
exactly like test_lint.py.  The repo-level gates — committed graph
matches the tree exactly, analysis clean — live in test_lint.py next to
their ledger siblings; here the REAL fleet map is pinned edge-by-edge so
a lock-domain change cannot slip through as "just drift".
"""

from __future__ import annotations

import json
import pathlib
import textwrap
import threading
import time

import pytest

from esac_tpu.lint.cli import main as lint_main
from esac_tpu.lint.lockgraph import (
    LOCK_GRAPH_NAME,
    analyze,
    build_graph,
    diff_graph,
    load_graph,
    lock_pass_needed,
    run_lock_rules,
    transitive_closure,
    write_graph,
)
from esac_tpu.lint.witness import LockWitness, WitnessLock

REPO = pathlib.Path(__file__).resolve().parent.parent


def _write(root: pathlib.Path, rel: str, text: str) -> str:
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(text))
    return rel


def _edge_pairs(graph: dict) -> set[tuple[str, str]]:
    return {(e["src"], e["dst"]) for e in graph["edges"]}


# --------------------------------------------------------------------------
# R12: lock-order graph

def test_r12_two_class_lock_cycle_is_flagged(tmp_path):
    """The golden trigger: Alpha takes its lock then calls into Beta
    (which locks), Beta takes its lock then calls back into Alpha — the
    classic AB/BA deadlock, invisible to per-class R10."""
    _write(tmp_path, "esac_tpu/serve/cycle.py", """\
        import threading

        class Alpha:
            def __init__(self, beta: "Beta"):
                self._lock = threading.Lock()
                self.beta = beta

            def ping(self):
                with self._lock:
                    self.beta.pong_locked()

            def ping_locked(self):
                with self._lock:
                    pass

        class Beta:
            def __init__(self, alpha: "Alpha"):
                self._lock = threading.Lock()
                self.alpha = alpha

            def pong(self):
                with self._lock:
                    self.alpha.ping_locked()

            def pong_locked(self):
                with self._lock:
                    pass
        """)
    a = analyze(tmp_path)
    assert _edge_pairs(a.graph()) == {
        ("Alpha._lock", "Beta._lock"), ("Beta._lock", "Alpha._lock"),
    }
    cycles = [f for f in a.findings if f.rule == "R12"]
    assert len(cycles) == 1
    assert cycles[0].text.startswith("cycle:")
    assert "Alpha._lock" in cycles[0].text and "Beta._lock" in cycles[0].text


def test_r12_condition_alias_is_one_node_not_an_edge(tmp_path):
    """The near-miss: a Condition built over the instance lock IS that
    lock.  Using the condition in one method and the lock in another is
    one node with an alias — never a second node, an edge, or a
    self-deadlock."""
    _write(tmp_path, "esac_tpu/serve/alias.py", """\
        import threading

        class Coalescer:
            def __init__(self):
                self._lock = threading.Lock()
                self._work = threading.Condition(self._lock)
                self.ring = []

            def submit(self, x):
                with self._work:
                    self.ring.append(x)
                    self._work.notify()

            def snapshot(self):
                with self._lock:
                    return list(self.ring)
        """)
    a = analyze(tmp_path)
    g = a.graph()
    assert list(g["nodes"]) == ["Coalescer._lock"]
    assert g["nodes"]["Coalescer._lock"]["aliases"] == ["_work"]
    assert g["edges"] == []
    assert a.findings == []


def test_r12_self_deadlock_through_helper_propagation(tmp_path):
    """A helper whose call sites hold the lock re-acquiring it is a
    self-deadlock on a non-reentrant Lock (the may-held fixpoint at
    work); the same shape over an RLock is reentrant by design."""
    _write(tmp_path, "esac_tpu/registry/selfdead.py", """\
        import threading

        class Bad:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self._inner()

            def _inner(self):
                with self._lock:
                    pass

        class Fine:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self._inner()

            def _inner(self):
                with self._lock:
                    pass
        """)
    findings = run_lock_rules(tmp_path)
    assert [f.rule for f in findings] == ["R12"]
    assert "Bad._inner" in findings[0].message
    assert "self-deadlock" in findings[0].message


def test_lock_graph_roundtrip_and_diff(tmp_path):
    _write(tmp_path, "esac_tpu/obs/pair.py", """\
        import threading

        class Inner:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                with self._lock:
                    pass

        class Outer:
            def __init__(self):
                self._lock = threading.Lock()
                self.inner = Inner()

            def drive(self):
                with self._lock:
                    self.inner.poke()
        """)
    g = build_graph(tmp_path)
    assert _edge_pairs(g) == {("Outer._lock", "Inner._lock")}
    path = tmp_path / "graph.json"
    write_graph(path, g)
    loaded = load_graph(path)
    findings, stale = diff_graph(loaded, g)
    assert findings == [] and stale == []
    assert load_graph(tmp_path / "missing.json") is None


def test_lock_graph_diff_new_edge_fails_removed_edge_is_stale():
    node = {"file": "x.py", "kind": "Lock", "aliases": []}
    base = {
        "nodes": {"A._lock": node, "B._lock": node},
        "edges": [{"src": "A._lock", "dst": "B._lock", "via": ["A.m"]}],
    }
    grown = {
        "nodes": dict(base["nodes"]),
        "edges": base["edges"] + [
            {"src": "B._lock", "dst": "A._lock", "via": ["B.n"]}
        ],
    }
    findings, stale = diff_graph(base, grown)
    assert [f.rule for f in findings] == ["R12"]
    assert findings[0].text == "edge:B._lock->A._lock"
    assert "unreviewed" in findings[0].message
    # The reverse direction — a committed edge no code path takes any
    # more — is stale (regenerate + review), never a failure.
    findings, stale = diff_graph(grown, base)
    assert findings == []
    assert any("no longer taken" in s for s in stale)
    # Same edge, different acquiring methods: drift, reported stale.
    moved = {
        "nodes": dict(base["nodes"]),
        "edges": [{"src": "A._lock", "dst": "B._lock", "via": ["A.other"]}],
    }
    findings, stale = diff_graph(base, moved)
    assert findings == []
    assert any("provenance" in s for s in stale)
    # Node drift both ways is stale.
    fewer = {"nodes": {"A._lock": node}, "edges": []}
    _, stale = diff_graph(base, fewer)
    assert any("no longer exists" in s for s in stale)
    _, stale = diff_graph(fewer, base)
    assert any("is new" in s for s in stale)


# --------------------------------------------------------------------------
# R13: blocking-under-lock

def test_r13_blocking_calls_under_lock(tmp_path):
    """Golden triggers: a sleep under the lock directly, an Event.wait
    under the lock, and a blocking call reached through a helper whose
    call site holds the lock (interprocedural propagation)."""
    _write(tmp_path, "esac_tpu/serve/blocky.py", """\
        import threading
        import time

        class Blocky:
            def __init__(self):
                self._lock = threading.Lock()
                self._ready = threading.Event()

            def sleepy(self):
                with self._lock:
                    time.sleep(0.1)

            def waity(self):
                with self._lock:
                    self._ready.wait()

            def outer(self):
                with self._lock:
                    self._slow()

            def _slow(self):
                time.sleep(0.1)
        """)
    findings = run_lock_rules(tmp_path)
    assert [f.rule for f in findings] == ["R13", "R13", "R13"]
    msgs = "\n".join(f.message for f in findings)
    assert "Blocky.sleepy" in msgs and "Blocky.waity" in msgs \
        and "Blocky._slow" in msgs
    assert all("Blocky._lock" in f.message for f in findings)


def test_r13_release_then_wait_and_coalescing_idiom_are_near_misses(tmp_path):
    """The two sanctioned shapes: snapshot under the lock then block
    OUTSIDE it (the _drain_probes / cache-load pattern), and the
    coalescing Condition.wait — the condition aliases the ONLY held
    lock, so the wait RELEASES it."""
    _write(tmp_path, "esac_tpu/registry/clean_wait.py", """\
        import threading
        import time

        class Clean:
            def __init__(self):
                self._lock = threading.Lock()
                self._work = threading.Condition(self._lock)
                self._ready = threading.Event()
                self.pending = []

            def drain(self):
                with self._lock:
                    batch = list(self.pending)
                    self.pending.clear()
                self._ready.wait()          # blocking AFTER release
                time.sleep(0.01)            # likewise
                return batch

            def coalesce(self):
                with self._work:
                    while not self.pending:
                        self._work.wait()   # releases the aliased lock
                    return self.pending.pop()
        """)
    assert run_lock_rules(tmp_path) == []


def test_r13_condition_wait_holding_a_second_lock_still_flags(tmp_path):
    """The alias allowlist releases ONLY the condition's own lock: a
    wait that keeps a second lock pinned across it blocks that lock's
    waiters unboundedly — flagged."""
    _write(tmp_path, "esac_tpu/serve/two_locks.py", """\
        import threading

        class TwoLocks:
            def __init__(self):
                self._stats_lock = threading.Lock()
                self._lock = threading.Lock()
                self._work = threading.Condition(self._lock)

            def bad_wait(self):
                with self._stats_lock:
                    with self._work:
                        self._work.wait()
        """)
    findings = run_lock_rules(tmp_path)
    r13 = [f for f in findings if f.rule == "R13"]
    assert len(r13) == 1
    assert "TwoLocks._stats_lock" in r13[0].message
    assert "TwoLocks._lock" not in r13[0].message  # released by the wait


def test_r13_typed_cross_class_blocking_and_suppression(tmp_path):
    """A blocking call reached through a TYPED attribute call chain
    (the dispatcher→cache shape) is flagged in the callee's file; an
    inline ``disable=R13(reason)`` on the blocking line silences it."""
    _write(tmp_path, "esac_tpu/registry/xcache.py", """\
        import threading

        class Loader:
            def __init__(self):
                self._lock = threading.Lock()
                self._fut = threading.Event()

            def fetch(self):
                self._fut.wait()
                return 1

        class Facade:
            def __init__(self):
                self._lock = threading.Lock()
                self.loader = Loader()

            def resolve(self):
                with self._lock:
                    return self.loader.fetch()
        """)
    findings = run_lock_rules(tmp_path)
    assert [f.rule for f in findings] == ["R13"]
    assert findings[0].path == "esac_tpu/registry/xcache.py"
    assert "Loader.fetch" in findings[0].message
    assert "Facade._lock" in findings[0].message
    # Reviewed case: the suppression sits on the blocking line.
    _write(tmp_path, "esac_tpu/registry/xcache.py", """\
        import threading

        class Loader:
            def __init__(self):
                self._lock = threading.Lock()
                self._fut = threading.Event()

            def fetch(self):
                self._fut.wait()  # graft-lint: disable=R13(fixture: bounded by test harness)
                return 1

        class Facade:
            def __init__(self):
                self._lock = threading.Lock()
                self.loader = Loader()

            def resolve(self):
                with self._lock:
                    return self.loader.fetch()
        """)
    assert run_lock_rules(tmp_path) == []


def test_r13_name_collision_still_walks_both_classes(tmp_path):
    """Two same-named classes in different fleet files drop out of TYPED
    dispatch only — their own acquisitions and blocking calls are still
    analyzed (review finding: dropping them from the walk entirely would
    hide a real deadlock behind a name collision)."""
    _write(tmp_path, "esac_tpu/serve/dup_a.py", """\
        import threading
        import time

        class Probe:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    time.sleep(0.1)
        """)
    _write(tmp_path, "esac_tpu/registry/dup_b.py", """\
        import threading

        class Probe:
            def __init__(self):
                self._lock = threading.Lock()

            def fine(self):
                with self._lock:
                    pass
        """)
    findings = run_lock_rules(tmp_path)
    assert [f.rule for f in findings] == ["R13"]
    assert findings[0].path == "esac_tpu/serve/dup_a.py"
    # Both collided classes' locks still appear (merged on the shared id).
    assert "Probe._lock" in build_graph(tmp_path)["nodes"]


# --------------------------------------------------------------------------
# the repo's own fleet map

def test_repo_fleet_lock_map_is_exactly_the_committed_ten_edges():
    """Pin the REAL fleet's lock-order graph edge-for-edge (DESIGN.md
    §15): dispatcher → {counter, histogram-vec, streaming-histogram}
    (accounting published inside the dispatch critical sections),
    registry health → counter (_record_event), health → manifest
    (_judge_locked's rollback-target reads), the FleetRouter's lock
    over the same obs-instrument leaves (ISSUE 14, mirroring the
    dispatcher's pattern), and — since ISSUE 15's causal traces
    (DESIGN.md §19) — dispatcher/router → TraceStore (completed-trace
    publication at the exactly-once _finish choke points; a leaf-lock
    deque append).  The timeline and rule-engine locks are ISOLATED
    leaf nodes by design (aggregate/evaluate take them with nothing
    held).  A new lock domain or a new nesting MUST show up here as a
    reviewed diff, not as drift."""
    g = build_graph(REPO)
    assert _edge_pairs(g) == {
        ("FleetRouter._lock", "CounterVec._lock"),
        ("FleetRouter._lock", "HistogramVec._lock"),
        ("FleetRouter._lock", "StreamingHistogram._lock"),
        ("FleetRouter._lock", "TraceStore._lock"),
        ("MicroBatchDispatcher._lock", "CounterVec._lock"),
        ("MicroBatchDispatcher._lock", "HistogramVec._lock"),
        ("MicroBatchDispatcher._lock", "StreamingHistogram._lock"),
        ("MicroBatchDispatcher._lock", "TraceStore._lock"),
        ("SceneRegistry._health_lock", "CounterVec._lock"),
        ("SceneRegistry._health_lock", "SceneManifest._lock"),
    }
    # ISSUE 15: the new locks exist as nodes, and timeline/rules are
    # leaf-isolated (no outgoing edges — nothing acquired under them).
    for node in ("TraceStore._lock", "Timeline._lock",
                 "RuleEngine._lock"):
        assert node in g["nodes"], node
        assert not any(src == node for src, _ in _edge_pairs(g)), node
    # The dispatcher's Condition aliases collapse onto one node.
    disp = g["nodes"]["MicroBatchDispatcher._lock"]
    assert disp["aliases"] == ["_space", "_work"]
    # And the whole fleet is R12/R13 clean — the first full-tree run's
    # verdict, pinned: the coalescing waits and the
    # snapshot-then-block-outside idioms must keep classifying as
    # near-misses, not findings.
    assert run_lock_rules(REPO) == []


def test_lock_pass_changed_mode_skip_condition():
    """--changed skips the (fleet-global) lock pass unless a
    serve/registry/obs/fleet/lint file changed — the jaxpr-layer skip,
    mirrored.  ISSUE 14: the replica-fleet tier is in scope."""
    assert lock_pass_needed(None)
    assert lock_pass_needed(["esac_tpu/serve/dispatcher.py"])
    assert lock_pass_needed(["esac_tpu/registry/cache.py"])
    assert lock_pass_needed(["esac_tpu/obs/metrics.py"])
    assert lock_pass_needed(["esac_tpu/lint/lockgraph.py"])
    assert lock_pass_needed(["esac_tpu/fleet/router.py"])
    assert not lock_pass_needed(
        ["esac_tpu/geometry/pnp.py", "bench.py", "LINT.md",
         "tests/test_serve.py"]
    )


# --------------------------------------------------------------------------
# CLI: the committed-artifact gate end to end

def _audited_fleet_tree(tmp_path):
    _write(tmp_path, "esac_tpu/lint/registry.py", "R11_WAIVED = {}\n")
    _write(tmp_path, "esac_tpu/serve/pairs.py", """\
        import threading

        class Inner:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                with self._lock:
                    pass

        class Outer:
            def __init__(self):
                self._lock = threading.Lock()
                self.inner = Inner()

            def drive(self):
                with self._lock:
                    self.inner.poke()
        """)


def test_cli_lock_graph_gate(tmp_path, capsys):
    """An audited tree without a committed graph fails typed (R12
    missing-lock-graph); --write-lock-graph + rerun is clean; a new
    nesting then fails as an unreviewed edge with a stable json id."""
    _audited_fleet_tree(tmp_path)
    rc = lint_main(["--root", str(tmp_path), "--no-jaxpr"])
    out = capsys.readouterr().out
    assert rc == 1 and "no committed lock-order graph" in out

    assert lint_main(["--root", str(tmp_path), "--no-jaxpr",
                      "--write-lock-graph"]) == 0
    capsys.readouterr()
    assert lint_main(["--root", str(tmp_path), "--no-jaxpr"]) == 0
    capsys.readouterr()

    # Grow a new nesting: Inner now calls BACK into a third lock.
    _write(tmp_path, "esac_tpu/serve/growth.py", """\
        import threading

        from esac_tpu.serve.pairs import Inner

        class Third:
            def __init__(self):
                self._lock = threading.Lock()
                self.inner = Inner()

            def drive(self):
                with self._lock:
                    self.inner.poke()
        """)
    rc = lint_main(["--root", str(tmp_path), "--no-jaxpr",
                    "--format", "json"])
    captured = capsys.readouterr()
    assert rc == 1
    objs = [json.loads(line) for line in
            captured.out.strip().splitlines()]
    edge = [o for o in objs if o["rule"] == "R12"]
    assert len(edge) == 1
    assert edge[0]["text"] == "edge:Third._lock->Inner._lock"
    assert edge[0]["id"].startswith("R12-")
    # New-node drift rides stderr as stale notes, not findings.
    assert "is new and not in the committed graph" in captured.err


def test_cli_json_r13_duplicate_ids_get_ordinals(tmp_path, capsys):
    """Two textually identical R13 lines share the line-number-free
    identity; the json ids still disambiguate via ordinals (the R12/R13
    ids ride the same driver contract as R1-R11)."""
    _write(tmp_path, "esac_tpu/serve/twice.py", """\
        import threading
        import time

        class Twice:
            def __init__(self):
                self._lock = threading.Lock()

            def a(self):
                with self._lock:
                    time.sleep(0.1)

            def b(self):
                with self._lock:
                    time.sleep(0.1)
        """)
    rc = lint_main(["--root", str(tmp_path), "--no-jaxpr",
                    "--format", "json"])
    ids = [json.loads(l)["id"] for l in
           capsys.readouterr().out.strip().splitlines()]
    assert rc == 1 and len(ids) == 2
    assert len(set(ids)) == 2
    assert ids[1] == ids[0] + "-2"


# --------------------------------------------------------------------------
# stale-suppression sweep

def test_stale_suppression_sweep(tmp_path):
    from esac_tpu.lint import run_layer1
    from esac_tpu.lint.suppress import (
        declared_suppressions,
        record_usage,
        stale_suppressions,
    )

    # One directive that actually masks a finding, one that masks nothing.
    _write(tmp_path, "esac_tpu/geometry/sup.py", """\
        import jax.numpy as jnp

        def n(v):
            return jnp.linalg.norm(v)  # graft-lint: disable=R2(fixture reason)

        def clean(v):
            return v  # graft-lint: disable=R4(nothing to mask here)
        """)
    with record_usage() as used:
        assert run_layer1(tmp_path) == []
    notes = stale_suppressions(declared_suppressions(tmp_path), used)
    assert len(notes) == 1
    assert "R4" in notes[0] and "sup.py:7" in notes[0]


def test_stale_r11_waiver_sweep(tmp_path):
    from esac_tpu.lint.ast_rules import stale_r11_waivers

    _write(tmp_path, "esac_tpu/lint/registry.py", """\
        R11_WAIVED = {
            "real_entry": "fixture: covered transitively",
            "ghost_entry": "fixture: the function this waived is gone",
        }
        """)
    _write(tmp_path, "esac_tpu/ransac/entries.py", """\
        import jax

        @jax.jit
        def real_entry(x):
            return x
        """)
    notes = stale_r11_waivers(tmp_path)
    assert len(notes) == 1
    assert "ghost_entry" in notes[0]
    # The repo's own waiver table carries no dangling names.
    assert stale_r11_waivers(REPO) == []


# --------------------------------------------------------------------------
# the runtime lock witness

def _mini_graph():
    node = {"file": "x.py", "kind": "Lock", "aliases": []}
    return {
        "nodes": {"A._lock": node, "B._lock": node, "C._lock": node},
        "edges": [
            {"src": "A._lock", "dst": "B._lock", "via": ["A.m"]},
            {"src": "B._lock", "dst": "C._lock", "via": ["B.m"]},
        ],
    }


def test_witness_subgraph_check_and_transitive_closure():
    """In-order acquisition passes; the closure sanctions A->C through
    B; an INJECTED out-of-order acquisition (C before A) is caught —
    the acceptance-criteria injection test."""
    w = LockWitness()
    a = w.wrap(threading.Lock(), "A._lock")
    b = w.wrap(threading.Lock(), "B._lock")
    c = w.wrap(threading.Lock(), "C._lock")
    committed = _mini_graph()
    with a:
        with b:
            with c:
                pass
    with a:
        with c:  # skips B: allowed — the committed ORDER has A before C
            pass
    assert w.violations(committed) == []
    assert ("A._lock", "C._lock") in transitive_closure(committed["edges"])
    w.assert_subgraph(committed)

    with c:
        with a:  # out of order: injected violation
            pass
    v = w.violations(committed)
    assert len(v) == 1 and v[0].startswith("C._lock->A._lock")
    with pytest.raises(AssertionError, match="C._lock->A._lock"):
        w.assert_subgraph(committed)


def test_witness_allows_rlock_reentry_like_the_static_pass():
    """Re-acquiring an RLock records a self-edge, but violations() must
    sanction it exactly as the static pass does ('reentrant by design');
    a self-edge on a non-reentrant Lock node still flags (review
    finding: the two halves must agree on RLock policy)."""
    w = LockWitness()
    r = w.wrap(threading.RLock(), "R._lock")
    with r:
        with r:
            pass
    committed = {
        "nodes": {"R._lock": {"file": "x.py", "kind": "RLock",
                              "aliases": []}},
        "edges": [],
    }
    assert w.violations(committed) == []
    # The same observation against a Lock-kind node is a violation.
    committed["nodes"]["R._lock"]["kind"] = "Lock"
    assert len(w.violations(committed)) == 1


def test_filter_suppressed_records_usage():
    """filter_suppressed participates in the stale-suppression sweep:
    a directive it honors counts as USED (review finding: the fallback
    path previously skipped recording and would report live directives
    stale)."""
    from esac_tpu.lint.findings import Finding
    from esac_tpu.lint.suppress import filter_suppressed, record_usage

    f = Finding("R2", "pkg/x.py", 2, "y = norm(v)", "msg")
    src = "# file\ny = norm(v)  # graft-lint: disable=R2(reviewed)\n"
    with record_usage() as used:
        out = filter_suppressed([f], {"pkg/x.py": src})
    assert out == []
    assert ("pkg/x.py", 2, "R2") in used


def test_witness_flags_locks_missing_from_committed_nodes():
    w = LockWitness()
    a = w.wrap(threading.Lock(), "A._lock")
    x = w.wrap(threading.Lock(), "Rogue._lock")
    with a:
        with x:
            pass
    v = w.violations(_mini_graph())
    assert len(v) == 1 and "missing from the committed graph" in v[0]


def test_witness_attach_rebuilds_conditions_and_records_holds():
    """attach() wraps in place and re-points Conditions at the wrapped
    lock, so the coalescing wait keeps working (wait releases, notify
    wakes) and hold times land in the histograms."""

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._work = threading.Condition(self._lock)
            self.items = []

    box = Box()
    w = LockWitness()
    w.attach(box, "_lock")
    assert isinstance(box._lock, WitnessLock)
    assert box._work._lock is box._lock  # the rebuilt alias

    def producer():
        time.sleep(0.05)
        with box._work:
            box.items.append(1)
            box._work.notify()

    t = threading.Thread(target=producer)
    t.start()
    with box._work:
        while not box.items:
            box._work.wait(5.0)
    t.join(5.0)
    assert box.items == [1]
    holds = w.hold_summary()
    assert holds["Box._lock"]["count"] >= 2
    assert holds["Box._lock"]["max"] < 5.0  # the wait RELEASED the lock


def test_witness_blocked_while_held_events_and_obs_collector():
    from esac_tpu.obs import MetricsRegistry

    w = LockWitness(blocked_threshold_s=1e-4)
    a = w.wrap(threading.Lock(), "A._lock")
    b = w.wrap(threading.Lock(), "B._lock")

    def holder():
        with a:
            time.sleep(0.05)

    t = threading.Thread(target=holder)
    t.start()
    time.sleep(0.01)
    with b:
        with a:  # blocks ~40ms while holding B — the R13 shape, observed
            pass
    t.join(5.0)
    events = w.blocked_events()
    assert any(e["wanted"] == "A._lock" and e["held"] == ["B._lock"]
               and e["waited_s"] > 0.01 for e in events)

    reg = MetricsRegistry()
    w.bind_obs(reg)
    snap = reg.snapshot()
    lw = snap["collectors"]["lock_witness"]
    assert "B._lock->A._lock" in lw["edges"]
    assert lw["holds"]["A._lock"]["count"] >= 2
    json.dumps(snap)  # the collector payload rides the snapshot contract


def test_witness_wrap_is_idempotent_and_off_means_plain_locks():
    """Double-attach never double-wraps; and with no witness in play a
    dispatcher's locks are plain threading primitives — the structural
    zero-overhead-when-off property (production code never imports the
    witness; tests attach explicitly)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from esac_tpu.ransac import RansacConfig
    from esac_tpu.serve import MicroBatchDispatcher

    w = LockWitness()

    class Box:
        def __init__(self):
            self._lock = threading.Lock()

    box = Box()
    w.attach(box, "_lock")
    first = box._lock
    w.attach(box, "_lock")
    assert box._lock is first  # idempotent

    disp = MicroBatchDispatcher(lambda t: t, RansacConfig(),
                                start_worker=False)
    try:
        assert not isinstance(disp._lock, WitnessLock)
        assert type(disp._lock).__module__ == "_thread"
    finally:
        disp.close()
