"""Checkpoint save/load roundtrip + C++ backend TSAN build."""

import pathlib
import subprocess

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from esac_tpu.models import ExpertNet
from esac_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_checkpoint_roundtrip(tmp_path):
    net = ExpertNet(stem_channels=(4, 8, 8), head_channels=8, head_depth=1,
                    compute_dtype=jnp.float32)
    x = jnp.ones((1, 16, 16, 3))
    params = net.init(jax.random.key(0), x)
    config = {"kind": "expert", "size": "test", "scene_center": [1.0, 2.0, 3.0]}
    save_checkpoint(tmp_path / "ck", params, config)
    params2, config2 = load_checkpoint(tmp_path / "ck")
    assert config2 == config
    y1 = net.apply(params, x)
    y2 = net.apply(params2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), atol=0)


def test_checkpoint_overwrite(tmp_path):
    net = ExpertNet(stem_channels=(4, 8, 8), head_channels=8, head_depth=1,
                    compute_dtype=jnp.float32)
    x = jnp.ones((1, 16, 16, 3))
    p1 = net.init(jax.random.key(1), x)
    p2 = net.init(jax.random.key(2), x)
    save_checkpoint(tmp_path / "ck", p1, {"v": 1})
    save_checkpoint(tmp_path / "ck", p2, {"v": 2})
    loaded, cfg = load_checkpoint(tmp_path / "ck")
    assert cfg == {"v": 2}
    np.testing.assert_allclose(
        np.asarray(jax.tree.leaves(loaded)[0]), np.asarray(jax.tree.leaves(p2)[0])
    )


def test_cpp_backend_runs_under_tsan(tmp_path):
    """SURVEY.md §5: TSAN on the C++ backend — built AND executed.

    Builds esac.cpp + esac_cpp/tsan_harness.cpp with -fsanitize=thread and
    runs the multi-threaded hypothesis loops (infer, gated) under 4 OpenMP
    threads.  One harness process per entry point: libgomp's thread pool
    makes only the first parallel region's fork TSAN-visible (see the
    harness docstring).  Any data race fails via TSAN_OPTIONS=exitcode=66.
    """
    import os

    exe = tmp_path / "tsan_harness"
    r = subprocess.run(
        ["g++", "-O1", "-g", "-fopenmp", "-fsanitize=thread",
         str(REPO / "esac_cpp" / "esac.cpp"),
         str(REPO / "esac_cpp" / "tsan_harness.cpp"), "-o", str(exe)],
        capture_output=True, text=True,
    )
    if r.returncode != 0 and "thread" in (r.stderr or ""):
        pytest.skip(f"TSAN unavailable: {r.stderr[:200]}")
    assert r.returncode == 0, r.stderr
    env = dict(os.environ, OMP_NUM_THREADS="4", TSAN_OPTIONS="exitcode=66")
    for mode in ("infer", "gated"):
        run = subprocess.run([str(exe), mode], capture_output=True,
                             text=True, env=env, timeout=300)
        assert run.returncode == 0, f"{mode}: {run.stderr[-2000:]}"
        assert "WARNING: ThreadSanitizer" not in run.stderr, run.stderr[-2000:]
        assert "tsan-harness-ok" in run.stdout


def test_stage_timer_and_counter():
    from esac_tpu.utils.profiling import StageTimer, hypotheses_per_sec

    t = StageTimer()
    x = jnp.ones(64)
    with t("op") as hold:
        hold.append(jnp.sum(x))
    with t("op"):
        pass
    assert t.counts["op"] == 2 and t.totals["op"] > 0
    assert "op" in t.summary()

    fn = jax.jit(lambda: jnp.sum(jnp.ones(128)))
    rate = hypotheses_per_sec(fn, (), n_hyps_per_call=128, repeats=3)
    assert rate > 0


def test_restore_tpu_written_checkpoint_on_cpu():
    """Checkpoints are topology-portable: ckpt_expert_synth0 was written on
    a TPU v5e in round 1; restoring on the CPU test mesh must yield host
    numpy arrays, not fail on the writer's device sharding."""
    import pathlib

    import numpy as np

    from esac_tpu.utils.checkpoint import load_checkpoint

    ck = pathlib.Path(__file__).parent.parent / "ckpts" / "ckpt_expert_synth0"
    params, cfg = load_checkpoint(ck)
    assert cfg["scene"] == "synth0"
    import jax

    leaves = jax.tree.leaves(params)
    assert leaves and all(isinstance(x, np.ndarray) for x in leaves)


# ~60s of double SIGKILL-resume training once orbax restore works again
# (orbax-drift FAILURE at seed); tier-1 keeps the cheap _tree_metadata
# regressions below — `pytest tests/` still runs this.
@pytest.mark.slow
def test_kill_and_resume_matches_uninterrupted(tmp_path):
    """SURVEY.md §5 build target: optimizer-state resume.  A run stopped at
    iteration 4 and resumed to 8 must reproduce the uninterrupted 8-iteration
    run exactly (params match; Adam state and data stream both restored)."""
    import subprocess
    import sys

    import jax
    import numpy as np

    from esac_tpu.utils.checkpoint import load_checkpoint

    repo = pathlib.Path(__file__).parent.parent

    def train(out, extra):
        subprocess.run(
            [sys.executable, str(repo / "train_expert.py"), "synth0", "--cpu",
             "--size", "test", "--batch", "2", "--iterations", "8",
             "--learningrate", "1e-3", "--output", str(out), *extra],
            capture_output=True, text=True, cwd=repo, timeout=600, check=True,
        )

    train(tmp_path / "full", [])
    train(tmp_path / "split", ["--stop-after", "4"])
    cfg = load_checkpoint(tmp_path / "split")[1]
    assert cfg["iteration"] == 4
    train(tmp_path / "split", ["--resume"])
    p_full, cfg_full = load_checkpoint(tmp_path / "full")
    p_split, cfg_split = load_checkpoint(tmp_path / "split")
    assert cfg_full["iteration"] == cfg_split["iteration"] == 8
    for a, b in zip(jax.tree.leaves(p_full), jax.tree.leaves(p_split)):
        np.testing.assert_allclose(a, b, atol=1e-6)


# ~17s CLI training; orbax-drift FAILURE at seed — same budget reasoning
# as test_kill_and_resume_matches_uninterrupted.
@pytest.mark.slow
def test_periodic_checkpointing(tmp_path):
    """--checkpoint-every N writes resume-capable state mid-run (the relay
    can stall mid-training — CLAUDE.md hazards — so long runs must not lose
    everything); the final save still lands at --iterations."""
    import subprocess
    import sys

    from esac_tpu.utils.checkpoint import load_checkpoint

    repo = pathlib.Path(__file__).parent.parent
    r = subprocess.run(
        [sys.executable, str(repo / "train_expert.py"), "synth0", "--cpu",
         "--size", "test", "--batch", "2", "--iterations", "6",
         "--checkpoint-every", "2", "--output", str(tmp_path / "ck")],
        capture_output=True, text=True, cwd=repo, timeout=600, check=True,
    )
    assert "@ iter 2" in r.stdout and "@ iter 4" in r.stdout
    # No redundant periodic save at the final iteration (the end save covers it).
    assert "@ iter 6" not in r.stdout
    assert load_checkpoint(tmp_path / "ck")[1]["iteration"] == 6


def test_train_state_old_fallback(tmp_path):
    """Death between save_train_state's two renames leaves the previous
    checkpoint at <path>.old; load_train_state must fall back to it."""
    import optax

    from esac_tpu.utils.checkpoint import load_train_state, save_train_state

    net = ExpertNet(stem_channels=(4, 8, 8), head_channels=8, head_depth=1,
                    compute_dtype=jnp.float32)
    x = jnp.ones((1, 16, 16, 3))
    params = net.init(jax.random.key(0), x)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    save_train_state(tmp_path / "ck", params, {"kind": "expert"}, opt_state, 5)
    # Simulate the rename window: the new dir vanished, .old remains.
    (tmp_path / "ck").rename(tmp_path / "ck.old")
    with pytest.warns(UserWarning, match="ck.old"):
        _, _, cfg, it = load_train_state(tmp_path / "ck", opt_state)
    assert it == 5 and cfg["kind"] == "expert"


def test_train_state_save_repairs_crash_state(tmp_path):
    """save_train_state after a crash-between-renames (path missing, .old
    present) must repair FIRST — never delete .old while it is the only
    surviving checkpoint — and end with a complete checkpoint, no .old."""
    import optax

    from esac_tpu.utils.checkpoint import load_train_state, save_train_state

    net = ExpertNet(stem_channels=(4, 8, 8), head_channels=8, head_depth=1,
                    compute_dtype=jnp.float32)
    x = jnp.ones((1, 16, 16, 3))
    params = net.init(jax.random.key(0), x)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    save_train_state(tmp_path / "ck", params, {"k": 1}, opt_state, 3)
    (tmp_path / "ck").rename(tmp_path / "ck.old")  # crash window state
    save_train_state(tmp_path / "ck", params, {"k": 2}, opt_state, 4)
    assert not (tmp_path / "ck.old").exists()
    assert not (tmp_path / "ck.staging").exists()
    _, _, cfg, it = load_train_state(tmp_path / "ck", opt_state)
    assert it == 4 and cfg["k"] == 2


# ~36s stop/resume CLI training; orbax-drift FAILURE at seed — same
# budget reasoning as test_kill_and_resume_matches_uninterrupted.
@pytest.mark.slow
def test_gating_resume_roundtrip(tmp_path):
    """Gating trainer: stop/resume preserves optimizer state (smoke)."""
    import subprocess
    import sys

    from esac_tpu.utils.checkpoint import load_checkpoint

    repo = pathlib.Path(__file__).parent.parent
    cmd = [sys.executable, str(repo / "train_gating.py"), "synth0", "synth1",
           "--cpu", "--size", "test", "--batch", "2", "--iterations", "6",
           "--output", str(tmp_path / "g")]
    subprocess.run(cmd + ["--stop-after", "3"], capture_output=True,
                   text=True, cwd=repo, timeout=600, check=True)
    assert (tmp_path / "g" / "opt_state").exists()
    r = subprocess.run(cmd + ["--resume"], capture_output=True, text=True,
                       cwd=repo, timeout=600, check=True)
    assert "resumed" in r.stdout
    assert load_checkpoint(tmp_path / "g")[1]["iteration"] == 6
