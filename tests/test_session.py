"""Temporal-session serving lane tests (ISSUE 20 acceptance).

The load-bearing claims:

- **all-invalid parity, through a live dispatcher**: a session frame
  whose prior mask is all-invalid (cold / never-tracked) rides the
  prior-slot program at the scene's full budget and reproduces the
  plain dispatch BIT-FOR-BIT — the prior slot is free until a prior
  actually wins (DESIGN.md §23; the entry-level pin lives in
  ``test_esac.py``-style direct calls below);
- **zero hot-path recompiles**: with the prior ladder prewarmed
  (``SceneRegistry.prewarm_programs(prior_slots=...)``), a session
  flapping tracked → lost → recovered never compiles a new program —
  the validity mask and the ``n_hyps`` lane carry every transition;
- **typed session errors**: an evicted session raises the retryable
  ``SessionEvictedError`` (a shed: admission said no), a never-opened
  or closed id the non-retryable ``SessionUnknownError``, and the
  observed (error, outcome) pairs stay inside the committed
  ``.fault_taxonomy.json``;
- **leaf lock**: ``SessionTable._lock`` is a committed LEAF of
  ``.lock_graph.json`` — the runtime witness must observe no edge out
  of it even under concurrent session traffic;
- **fleet affinity + budget passthrough**: a session over a
  ``FleetRouter`` keeps its scene's replica affinity and its tracked
  frames dispatch at the shrunken ``n_hyps`` override.
"""

import dataclasses
import pathlib
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from esac_tpu.models import ExpertNet, GatingNet
from esac_tpu.ransac import RansacConfig, esac_infer, esac_infer_prior
from esac_tpu.registry import (
    SceneEntry,
    SceneManifest,
    ScenePreset,
    SceneRegistry,
)
from esac_tpu.serve import (
    MicroBatchDispatcher,
    SessionEvictedError,
    SessionPolicy,
    SessionRouter,
    SessionTable,
    SessionUnknownError,
    ShedError,
    SLOPolicy,
)

REPO = pathlib.Path(__file__).resolve().parent.parent

H = W = 16
M = 2
FULL_HYPS = 8
TRACK_HYPS = 4
P = 3
PRESET = ScenePreset(
    height=H, width=W, num_experts=M,
    stem_channels=(2, 2, 2), head_channels=2, head_depth=1,
    gating_channels=(2,), compute_dtype="float32", gated=True,
)
CFG = RansacConfig(n_hyps=FULL_HYPS, refine_iters=2, polish_iters=1,
                   frame_buckets=(1,), serve_max_wait_ms=0.0,
                   serve_queue_depth=64)
POSE_KEYS = ("rvec", "tvec", "expert", "inlier_frac", "gating_probs")


def _params(seed=0):
    expert = ExpertNet(
        scene_center=(0.0, 0.0, 0.0), stem_channels=PRESET.stem_channels,
        head_channels=PRESET.head_channels, head_depth=PRESET.head_depth,
        compute_dtype=jnp.float32,
    )
    gating = GatingNet(num_experts=M, channels=PRESET.gating_channels,
                       compute_dtype=jnp.float32)
    img0 = jnp.zeros((1, H, W, 3))
    return {
        "expert": jax.vmap(lambda k: expert.init(k, img0))(
            jax.random.split(jax.random.key(seed), M)
        ),
        "gating": gating.init(jax.random.key(seed + 100), img0),
        "centers": jnp.asarray(
            np.asarray([[0.0, 0.0, 2.0]], np.float32)
            + np.arange(M, dtype=np.float32)[:, None] * 0.1
        ),
        "c": jnp.asarray([W / 2.0, H / 2.0]),
        "f": jnp.float32(20.0),
    }


@pytest.fixture(scope="module")
def registry():
    params = {"a": _params(0)}
    m = SceneManifest()
    m.add(SceneEntry(
        scene_id="a", version=1, expert_ckpt="unused",
        gating_ckpt="unused", preset=PRESET, ransac=CFG,
    ))
    return SceneRegistry(m, loader=lambda e: params[e.scene_id])


def _frame(i):
    return {
        "key": jax.random.fold_in(jax.random.key(7), i),
        "image": np.asarray(jax.random.uniform(
            jax.random.fold_in(jax.random.key(42), i), (H, W, 3)
        )),
    }


def _bitwise(a, b, keys=POSE_KEYS):
    return all(
        np.array_equal(np.asarray(a[k]), np.asarray(b[k])) for k in keys
    )


# ---------------- policy / table host logic ----------------

def test_policy_validation():
    with pytest.raises(ValueError):
        SessionPolicy(prior_slots=0)
    with pytest.raises(ValueError):
        SessionPolicy(track_n_hyps=0)
    with pytest.raises(ValueError):
        SessionPolicy(track_loss_frac=0.0)
    with pytest.raises(ValueError):
        SessionPolicy(track_loss_frac=1.0)
    with pytest.raises(ValueError):
        SessionPolicy(track_enter_frac=1.5)
    with pytest.raises(ValueError):
        SessionPolicy(max_sessions=0)
    # enter bar defaults to the loss bar; explicit hysteresis sticks.
    assert SessionPolicy(track_loss_frac=0.2).enter_frac == 0.2
    assert SessionPolicy(track_loss_frac=0.2,
                         track_enter_frac=0.4).enter_frac == 0.4


def test_table_transitions_and_motion_priors():
    """cold -> tracked -> lost walks the documented transition machine;
    the tracked plan carries (last winner, constant-velocity
    extrapolation) in slots 0/1 and clears ALL motion state on loss."""
    pol = SessionPolicy(prior_slots=P, track_n_hyps=TRACK_HYPS,
                        track_loss_frac=0.3, track_enter_frac=0.5)
    t = SessionTable(pol)
    t.open("s", scene="a", full_n_hyps=FULL_HYPS)

    scene, rk, n_hyps, rv, tv, valid, tracked = t.plan("s")
    assert (scene, rk, n_hyps, tracked) == ("a", None, FULL_HYPS, False)
    assert not valid.any()

    # Full-budget winner below the enter bar: still cold.
    assert t.observe("s", np.ones(3), np.ones(3), 0.4, False) == "cold"
    assert t.plan("s")[6] is False
    # At the bar: enters tracking; slot 0 = the winner, slot 1 the
    # constant-velocity extrapolation (the cold frame's winner counts
    # as the previous pose — a full-budget winner is still a winner).
    r1, t1 = np.asarray([0.1, 0.0, 0.0]), np.asarray([1.0, 0.0, 0.0])
    assert t.observe("s", r1, t1, 0.6, False) == "tracked"
    _, _, n_hyps, rv, tv, valid, tracked = t.plan("s")
    assert tracked and n_hyps == TRACK_HYPS
    assert valid.tolist() == [True, True, False]
    np.testing.assert_array_equal(rv[0], r1.astype(np.float32))
    np.testing.assert_allclose(rv[1], 2.0 * r1 - np.ones(3), rtol=1e-6)
    # Second winner: slot 1 is the constant-velocity extrapolation
    # 2*last - prev, linear in the rvec/tvec coordinates.
    r2, t2 = np.asarray([0.2, 0.0, 0.0]), np.asarray([1.5, 0.0, 0.0])
    assert t.observe("s", r2, t2, 0.7, True) == "tracked"
    _, _, _, rv, tv, valid, _ = t.plan("s")
    assert valid.tolist() == [True, True, False]
    np.testing.assert_allclose(rv[1], 2.0 * r2 - r1, rtol=1e-6)
    np.testing.assert_allclose(tv[1], 2.0 * t2 - t1, rtol=1e-6)

    # Tracked winner under the loss bar: lost, motion state cleared,
    # NEXT frame plans the full budget with no priors.
    assert t.observe("s", r2, t2, 0.1, True) == "lost"
    _, _, n_hyps, _, _, valid, tracked = t.plan("s")
    assert not tracked and n_hyps == FULL_HYPS and not valid.any()

    s = t.stats()
    assert s["frames"] == 4 and s["tracked_frames"] == 2
    assert s["track_losses"] == 1 and s["track_entries"] == 1
    assert s["budget_saved_hyps"] == 2 * (FULL_HYPS - TRACK_HYPS)


def test_table_eviction_and_unknown_are_typed():
    pol = SessionPolicy(max_sessions=1)
    t = SessionTable(pol)
    t.open("a")
    t.open("b")  # evicts "a" (LRU, capacity 1)
    with pytest.raises(SessionEvictedError) as ei:
        t.plan("a")
    assert isinstance(ei.value, ShedError)
    assert ei.value.retryable and ei.value.wire_name == "session_evicted"
    with pytest.raises(SessionUnknownError) as ui:
        t.plan("never-opened")
    assert not ui.value.retryable
    assert ui.value.wire_name == "session_unknown"
    # close() is the caller's own action -> unknown, not evicted.
    assert t.close("b")
    with pytest.raises(SessionUnknownError):
        t.plan("b")
    # A winner landing after eviction is a no-op, not a crash.
    assert t.observe("a", np.zeros(3), np.zeros(3), 0.9, False) == "evicted"
    # Re-opening an evicted id resumes cold.
    t.open("a")
    assert t.plan("a")[6] is False
    # The observed pair is a committed .fault_taxonomy.json edge.
    from esac_tpu.lint.witness import OutcomeWitness

    ow = OutcomeWitness.from_repo(REPO)
    ow.observe("SessionEvictedError", "shed")
    ow.assert_consistent()


# ---------------- entry-level parity (the §23 pin) ----------------

def test_prior_entry_all_invalid_is_bitwise_dense():
    frame = _frame(0)
    pixels = jnp.stack(jnp.meshgrid(
        jnp.arange(2.0, W, 4.0), jnp.arange(2.0, H, 4.0)
    ), -1).reshape(-1, 2)
    coords = jax.random.normal(jax.random.key(3), (M, pixels.shape[0], 3))
    f, c = jnp.float32(20.0), jnp.asarray([W / 2.0, H / 2.0])
    cfg = RansacConfig(n_hyps=FULL_HYPS, refine_iters=2, polish_iters=1)
    plain = esac_infer(jax.random.key(5), jnp.zeros(M), coords, pixels,
                       f, c, cfg)
    prior = esac_infer_prior(
        jax.random.key(5), jnp.zeros(M), coords, pixels, f, c,
        jnp.zeros((P, 3)), jnp.zeros((P, 3)), jnp.zeros((P,), bool), cfg,
    )
    assert not bool(prior["prior_hit"])
    assert int(prior["prior_slot"]) == P  # sentinel: sampled stream won
    keys = [k for k in ("rvec", "tvec", "expert", "inlier_frac", "score",
                        "scores") if k in plain and k in prior]
    assert {"rvec", "tvec", "expert", "inlier_frac"} <= set(keys)
    for k in keys:
        assert np.array_equal(np.asarray(prior[k]), np.asarray(plain[k])), k


def test_prior_entry_valid_prior_can_win():
    """A valid prior equal to a near-perfect pose beats the sampled
    stream on a frame whose coords support it — the slot is live, not
    decorative."""
    from esac_tpu.geometry import backproject_at_depth, rodrigues

    rvec = jnp.asarray([0.1, -0.2, 0.05])
    tvec = jnp.asarray([0.0, 0.1, 2.0])
    pixels = jnp.stack(jnp.meshgrid(
        jnp.arange(2.0, W, 4.0), jnp.arange(2.0, H, 4.0)
    ), -1).reshape(-1, 2)
    f, c = jnp.float32(20.0), jnp.asarray([W / 2.0, H / 2.0])
    # Coords consistent with (rvec, tvec) at depth 2 plus enough noise
    # that the sampled minimal solves are imperfect while the injected
    # prior IS the noise-free pose — the prior must score strictly best.
    world = backproject_at_depth(rodrigues(rvec), tvec, pixels, f, c, 2.0)
    world = world + 0.05 * jax.random.normal(jax.random.key(8), world.shape)
    coords = jnp.stack([world, world + 0.5])  # expert 1 is junk
    prv = jnp.zeros((P, 3)).at[1].set(rvec)
    ptv = jnp.zeros((P, 3)).at[1].set(tvec)
    pvalid = jnp.zeros((P,), bool).at[1].set(True)
    cfg = RansacConfig(n_hyps=4, refine_iters=2, polish_iters=1)
    out = esac_infer_prior(jax.random.key(1), jnp.zeros(M), coords, pixels,
                           f, c, prv, ptv, pvalid, cfg)
    assert bool(out["prior_hit"])
    assert int(out["prior_slot"]) == 1
    assert int(out["expert"]) == 0


# ---------------- dispatcher-level parity + zero recompiles ----------------

def test_session_lane_parity_and_zero_recompiles(registry):
    """The tentpole acceptance: through a LIVE worker-backed dispatcher,
    a cold session frame is bitwise the plain dispatch, and a session
    flapping tracked -> lost -> recovered compiles nothing beyond the
    prewarmed ladder."""
    reg = registry
    compiled = reg.prewarm_programs(
        "a", frame_buckets=(1,), route_ks=(None,),
        n_hyps_overrides=(None, TRACK_HYPS), prior_slots=P,
    )
    pol = SessionPolicy(prior_slots=P, track_n_hyps=TRACK_HYPS,
                        track_loss_frac=0.999, track_enter_frac=0.5)
    disp = reg.dispatcher(CFG, slo=SLOPolicy(watchdog_ms=60_000.0))
    try:
        router = SessionRouter(disp, pol)
        router.open("s", scene="a", full_n_hyps=FULL_HYPS)

        plain = disp.infer_one(_frame(0), scene="a", timeout=30.0)
        via_session = router.infer_frame("s", _frame(0), timeout=30.0)
        assert via_session["session_tracked"] is False
        assert _bitwise(via_session, plain)

        # Seed tracking deterministically, then flap: the tracked frame
        # (loss bar 0.999) drops the track, the recovery frame runs the
        # full budget, re-enters if the winner clears the bar.
        router.table.observe("s", np.zeros(3, np.float32),
                             np.zeros(3, np.float32), 1.0 - 1e-6, False)
        before = reg.compile_cache_size()
        transitions, tracked = [], []
        for i in range(6):
            out = router.infer_frame("s", _frame(i), timeout=30.0)
            transitions.append(out["session_transition"])
            tracked.append(out["session_tracked"])
        assert tracked[0] is True          # seeded -> tracked lane
        assert transitions[0] == "lost"    # bar 0.999 unreachable
        assert tracked[1] is False         # recovery = full budget
        assert reg.compile_cache_size() == before == compiled
        assert router.table.stats()["track_losses"] >= 1
    finally:
        disp.close()


def test_session_lock_is_leaf_under_concurrent_traffic(registry):
    """Runtime lock witness: concurrent sessions through a live
    dispatcher observe NO edge out of SessionTable._lock, and the whole
    observed order stays inside the committed .lock_graph.json."""
    from esac_tpu.lint.lockgraph import LOCK_GRAPH_NAME, load_graph
    from esac_tpu.lint.witness import LockWitness

    reg = registry
    disp = reg.dispatcher(CFG, slo=SLOPolicy(watchdog_ms=60_000.0),
                          start_worker=False)
    witness = LockWitness()
    router = SessionRouter(disp, SessionPolicy(
        prior_slots=P, track_n_hyps=TRACK_HYPS, track_loss_frac=1e-6,
        track_enter_frac=0.5,
    ))
    witness.attach_fleet(disp=disp, session_router=router)
    disp.start()
    try:
        errors = []

        def stream(sid):
            try:
                router.open(sid, scene="a", full_n_hyps=FULL_HYPS)
                for i in range(4):
                    router.infer_frame(sid, _frame(i), timeout=30.0)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [threading.Thread(target=stream, args=(f"s{t}",))
                   for t in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        assert errors == []
    finally:
        disp.close()

    committed = load_graph(REPO / LOCK_GRAPH_NAME)
    assert committed is not None
    assert "SessionTable._lock" in committed.get("nodes", {})
    witness.assert_subgraph(committed)
    holds = witness.snapshot()["holds"]
    assert any("SessionTable._lock" in str(k) for k in holds)
    assert not any(src.startswith("SessionTable._lock")
                   for (src, _dst) in witness.edges())


def test_track_loss_event_rides_sampled_trace(registry):
    """A track loss on a traced request lands as a session:track_loss
    event span on the §19 causal trace."""
    reg = registry
    disp = reg.dispatcher(CFG, slo=SLOPolicy(watchdog_ms=60_000.0),
                          trace=True)
    try:
        router = SessionRouter(disp, SessionPolicy(
            prior_slots=P, track_n_hyps=TRACK_HYPS,
            track_loss_frac=0.999, track_enter_frac=0.5,
        ))
        router.open("s", scene="a", full_n_hyps=FULL_HYPS)
        router.table.observe("s", np.zeros(3, np.float32),
                             np.zeros(3, np.float32), 0.9, False)
        out = router.infer_frame("s", _frame(0), timeout=30.0)
        assert out["session_transition"] == "lost"
        events = [
            s for t in disp._trace_store.traces()
            for s in list(t.spans) if s.name == "session:track_loss"
        ]
        assert len(events) == 1
        assert events[0].annotations["session"] == "s"
    finally:
        disp.close()


# ---------------- obs collector ----------------

def test_session_collector_in_unified_snapshot(registry):
    disp = registry.dispatcher(CFG, start_worker=False)
    router = SessionRouter(disp, SessionPolicy())
    router.open("x")
    snap = disp.obs.snapshot()
    sess = snap["collectors"]["session"]
    assert sess["sessions"] == 1 and sess["opened"] == 1
    assert router.close("x")
    assert disp.obs.snapshot()["collectors"]["session"]["closed"] == 1
    disp.close()


# ---------------- fleet affinity + budget passthrough ----------------

def test_fleet_affinity_and_tracked_budget_passthrough():
    """Over a FleetRouter, a session's frames keep their scene's replica
    affinity and tracked frames carry the shrunken n_hyps override."""
    from esac_tpu.fleet import FleetPolicy, FleetRouter, Replica

    cfg = RansacConfig(n_hyps=FULL_HYPS, refine_iters=2, frame_buckets=(1,),
                       serve_max_wait_ms=0.0, serve_queue_depth=64)
    seen = []  # (replica, n_hyps) per dispatch
    mu = threading.Lock()

    def infer(idx):
        def fn(tree, scene=None, route_k=None, n_hyps=None):
            lanes = tree["x"].shape[0]
            with mu:
                seen.append((idx, n_hyps))
            return {
                "rvec": np.zeros((lanes, 3), np.float32),
                "tvec": np.zeros((lanes, 3), np.float32),
                "inlier_frac": np.full(lanes, 0.9, np.float32),
                "rep": np.full(lanes, idx, np.int32),
            }
        return fn

    slo = SLOPolicy(watchdog_ms=60_000.0)
    reps = [Replica(f"r{i}", MicroBatchDispatcher(infer(i), cfg, slo=slo))
            for i in range(2)]
    router = FleetRouter(reps, FleetPolicy(poll_ms=2.0))
    try:
        sess = SessionRouter(router, SessionPolicy(
            prior_slots=P, track_n_hyps=TRACK_HYPS,
            track_loss_frac=0.1, track_enter_frac=0.5,
        ))
        sess.open("s", scene="sc", full_n_hyps=FULL_HYPS)
        homes = set()
        for i in range(5):
            out = sess.infer_frame(
                "s", {"x": np.full(2, float(i), np.float32)}, timeout=30.0
            )
            homes.add(int(np.asarray(out["rep"])))
            assert out["session_tracked"] is (i > 0)
        # One home replica end to end (scene affinity unbroken by the
        # shrunken-budget lane), and the budget ladder: full first
        # frame, tracked override after.
        assert len(homes) == 1
        budgets = [h for _r, h in seen]
        assert budgets[0] == FULL_HYPS
        assert set(budgets[1:]) == {TRACK_HYPS}
        stats = router.affinity_stats()
        assert stats["affinity"] >= 4
    finally:
        router.close()
