"""Backend equivalence: cpp vs jax (SURVEY.md §4, the decisive test class).

RNG streams cannot be bit-identical across backends (different generators by
design; the sampling contract in esac_tpu/ransac/sampling.py documents
this), so equivalence is statistical: same inputs -> both backends localize
within tolerance of GT and of each other, and score the same pose equally.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from esac_tpu.backends import cpp_available, esac_infer_cpp
from esac_tpu.data import make_correspondence_frame
from esac_tpu.geometry import pose_errors, rodrigues
from esac_tpu.ransac import RansacConfig, dsac_infer
from esac_tpu.ransac.scoring import reprojection_error_map, soft_inlier_score

pytestmark = pytest.mark.skipif(not cpp_available(), reason="cpp backend unavailable")

F = 525.0
C = (320.0, 240.0)


@pytest.mark.parametrize("seed", [1, 2])
def test_backends_agree_on_pose(seed):
    frame = make_correspondence_frame(
        jax.random.key(seed), noise=0.01, outlier_frac=0.3
    )
    co, px = np.asarray(frame["coords"]), np.asarray(frame["pixels"])
    cpp = esac_infer_cpp(co, px, F, C, n_hyps=256, seed=seed)
    jout = dsac_infer(
        jax.random.key(seed), frame["coords"], frame["pixels"],
        jnp.float32(F), jnp.asarray(C), RansacConfig(n_hyps=256),
    )
    R_gt, t_gt = rodrigues(frame["rvec"]), frame["tvec"]
    r_c, t_c = pose_errors(jnp.asarray(cpp["R"], jnp.float32), jnp.asarray(cpp["t"], jnp.float32), R_gt, t_gt)
    r_j, t_j = pose_errors(rodrigues(jout["rvec"]), jout["tvec"], R_gt, t_gt)
    assert r_c < 1.0 and t_c < 0.02, f"cpp off: {r_c} deg {t_c} m"
    assert r_j < 1.0 and t_j < 0.02, f"jax off: {r_j} deg {t_j} m"
    # Backends agree with each other (both anchored to GT).
    r_x, t_x = pose_errors(
        jnp.asarray(cpp["R"], jnp.float32), jnp.asarray(cpp["t"], jnp.float32),
        rodrigues(jout["rvec"]), jout["tvec"],
    )
    assert r_x < 1.5 and t_x < 0.03


def test_scoring_functions_match():
    """The jax soft-inlier score of the cpp winner must match cpp's own score."""
    frame = make_correspondence_frame(jax.random.key(3), noise=0.02, outlier_frac=0.2)
    co, px = np.asarray(frame["coords"]), np.asarray(frame["pixels"])
    cpp = esac_infer_cpp(co, px, F, C, n_hyps=128, seed=3)
    from esac_tpu.geometry.rotations import so3_log

    rvec = so3_log(jnp.asarray(cpp["R"], jnp.float32))
    errors = reprojection_error_map(
        rvec[None], jnp.asarray(cpp["t"], jnp.float32)[None],
        frame["coords"], frame["pixels"], jnp.float32(F), jnp.asarray(C),
    )
    jax_score = float(soft_inlier_score(errors, 10.0, 0.5)[0])
    assert jax_score == pytest.approx(cpp["score"], rel=0.01)


def test_cpp_score_distribution_sane():
    """Score curves statistically matched: both backends' hypothesis pools
    should contain high-inlier hypotheses at similar rates."""
    frame = make_correspondence_frame(jax.random.key(4), noise=0.01)
    co, px = np.asarray(frame["coords"]), np.asarray(frame["pixels"])
    n_cells = co.shape[0]
    cpp = esac_infer_cpp(co, px, F, C, n_hyps=256, seed=4, return_scores=True)
    cpp_frac = (cpp["scores"] > 0.5 * n_cells).mean()

    from esac_tpu.ransac.kernel import generate_hypotheses

    cfg = RansacConfig(n_hyps=256)
    rv, tv = generate_hypotheses(
        jax.random.key(4), frame["coords"], frame["pixels"],
        jnp.float32(F), jnp.asarray(C), cfg,
    )
    errors = reprojection_error_map(
        rv, tv, frame["coords"], frame["pixels"], jnp.float32(F), jnp.asarray(C)
    )
    jax_frac = float((soft_inlier_score(errors, cfg.tau, cfg.beta) > 0.5 * n_cells).mean())
    assert cpp_frac > 0.3 and jax_frac > 0.3
    assert abs(cpp_frac - jax_frac) < 0.25, (cpp_frac, jax_frac)


def test_multi_expert_cpp_finds_correct_expert():
    """Native multi-expert loop: consensus picks the right expert and pose."""
    frame = make_correspondence_frame(jax.random.key(7), noise=0.01)
    n = frame["coords"].shape[0]
    correct = 2
    maps = np.stack([
        np.asarray(frame["coords"]) if m == correct
        else np.asarray(jax.random.uniform(jax.random.key(50 + m), (n, 3), maxval=5.0))
        for m in range(4)
    ])
    from esac_tpu.backends import esac_infer_multi_cpp

    out = esac_infer_multi_cpp(maps, np.asarray(frame["pixels"]), F, C,
                               n_hyps_per_expert=128, seed=7)
    assert out["expert"] == correct
    assert out["expert_scores"].shape == (4,)
    assert out["expert_scores"].argmax() == correct
    r_err, t_err = pose_errors(
        jnp.asarray(out["R"], jnp.float32), jnp.asarray(out["t"], jnp.float32),
        rodrigues(frame["rvec"]), frame["tvec"],
    )
    assert r_err < 1.0 and t_err < 0.02


def test_cpp_rejects_degenerate_cell_count():
    """ADVICE r1: n_cells < 4 used to spin forever in the distinct-index
    rejection loop; it must fail the frame immediately instead."""
    if not cpp_available():
        pytest.skip("cpp backend unavailable")
    coords = np.zeros((3, 3), dtype=np.float32)
    pixels = np.zeros((3, 2), dtype=np.float32)
    out = esac_infer_cpp(coords, pixels, 500.0, (80.0, 60.0), n_hyps=8,
                         return_scores=True)
    assert out["n_valid"] == 0
    assert (out["scores"] == -1.0).all()
