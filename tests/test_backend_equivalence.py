"""Backend equivalence: cpp vs jax (SURVEY.md §4, the decisive test class).

RNG streams cannot be bit-identical across backends (different generators by
design; the sampling contract in esac_tpu/ransac/sampling.py documents
this), so equivalence is statistical: same inputs -> both backends localize
within tolerance of GT and of each other, and score the same pose equally.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from esac_tpu.backends import cpp_available, esac_infer_cpp
from esac_tpu.data import make_correspondence_frame
from esac_tpu.geometry import pose_errors, rodrigues
from esac_tpu.ransac import RansacConfig, dsac_infer
from esac_tpu.ransac.scoring import reprojection_error_map, soft_inlier_score

pytestmark = pytest.mark.skipif(not cpp_available(), reason="cpp backend unavailable")

F = 525.0
C = (320.0, 240.0)


@pytest.mark.parametrize("seed", [1, 2])
def test_backends_agree_on_pose(seed):
    frame = make_correspondence_frame(
        jax.random.key(seed), noise=0.01, outlier_frac=0.3
    )
    co, px = np.asarray(frame["coords"]), np.asarray(frame["pixels"])
    cpp = esac_infer_cpp(co, px, F, C, n_hyps=256, seed=seed)
    jout = dsac_infer(
        jax.random.key(seed), frame["coords"], frame["pixels"],
        jnp.float32(F), jnp.asarray(C), RansacConfig(n_hyps=256),
    )
    R_gt, t_gt = rodrigues(frame["rvec"]), frame["tvec"]
    r_c, t_c = pose_errors(jnp.asarray(cpp["R"], jnp.float32), jnp.asarray(cpp["t"], jnp.float32), R_gt, t_gt)
    r_j, t_j = pose_errors(rodrigues(jout["rvec"]), jout["tvec"], R_gt, t_gt)
    assert r_c < 1.0 and t_c < 0.02, f"cpp off: {r_c} deg {t_c} m"
    assert r_j < 1.0 and t_j < 0.02, f"jax off: {r_j} deg {t_j} m"
    # Backends agree with each other (both anchored to GT).
    r_x, t_x = pose_errors(
        jnp.asarray(cpp["R"], jnp.float32), jnp.asarray(cpp["t"], jnp.float32),
        rodrigues(jout["rvec"]), jout["tvec"],
    )
    assert r_x < 1.5 and t_x < 0.03


def test_scoring_functions_match():
    """The jax soft-inlier score of the cpp winner must match cpp's own score."""
    frame = make_correspondence_frame(jax.random.key(3), noise=0.02, outlier_frac=0.2)
    co, px = np.asarray(frame["coords"]), np.asarray(frame["pixels"])
    cpp = esac_infer_cpp(co, px, F, C, n_hyps=128, seed=3)
    from esac_tpu.geometry.rotations import so3_log

    rvec = so3_log(jnp.asarray(cpp["R"], jnp.float32))
    errors = reprojection_error_map(
        rvec[None], jnp.asarray(cpp["t"], jnp.float32)[None],
        frame["coords"], frame["pixels"], jnp.float32(F), jnp.asarray(C),
    )
    jax_score = float(soft_inlier_score(errors, 10.0, 0.5)[0])
    assert jax_score == pytest.approx(cpp["score"], rel=0.01)


def test_cpp_score_distribution_sane():
    """Score curves statistically matched: both backends' hypothesis pools
    should contain high-inlier hypotheses at similar rates."""
    frame = make_correspondence_frame(jax.random.key(4), noise=0.01)
    co, px = np.asarray(frame["coords"]), np.asarray(frame["pixels"])
    n_cells = co.shape[0]
    cpp = esac_infer_cpp(co, px, F, C, n_hyps=256, seed=4, return_scores=True)
    cpp_frac = (cpp["scores"] > 0.5 * n_cells).mean()

    from esac_tpu.ransac.kernel import generate_hypotheses

    cfg = RansacConfig(n_hyps=256)
    rv, tv = generate_hypotheses(
        jax.random.key(4), frame["coords"], frame["pixels"],
        jnp.float32(F), jnp.asarray(C), cfg,
    )
    errors = reprojection_error_map(
        rv, tv, frame["coords"], frame["pixels"], jnp.float32(F), jnp.asarray(C)
    )
    jax_frac = float((soft_inlier_score(errors, cfg.tau, cfg.beta) > 0.5 * n_cells).mean())
    assert cpp_frac > 0.3 and jax_frac > 0.3
    assert abs(cpp_frac - jax_frac) < 0.25, (cpp_frac, jax_frac)


def test_multi_expert_cpp_finds_correct_expert():
    """Native multi-expert loop: consensus picks the right expert and pose."""
    frame = make_correspondence_frame(jax.random.key(7), noise=0.01)
    n = frame["coords"].shape[0]
    correct = 2
    maps = np.stack([
        np.asarray(frame["coords"]) if m == correct
        else np.asarray(jax.random.uniform(jax.random.key(50 + m), (n, 3), maxval=5.0))
        for m in range(4)
    ])
    from esac_tpu.backends import esac_infer_multi_cpp

    out = esac_infer_multi_cpp(maps, np.asarray(frame["pixels"]), F, C,
                               n_hyps_per_expert=128, seed=7)
    assert out["expert"] == correct
    assert out["expert_scores"].shape == (4,)
    assert out["expert_scores"].argmax() == correct
    r_err, t_err = pose_errors(
        jnp.asarray(out["R"], jnp.float32), jnp.asarray(out["t"], jnp.float32),
        rodrigues(frame["rvec"]), frame["tvec"],
    )
    assert r_err < 1.0 and t_err < 0.02


def test_cpp_rejects_degenerate_cell_count():
    """ADVICE r1: n_cells < 4 used to spin forever in the distinct-index
    rejection loop; it must fail the frame immediately instead."""
    if not cpp_available():
        pytest.skip("cpp backend unavailable")
    coords = np.zeros((3, 3), dtype=np.float32)
    pixels = np.zeros((3, 2), dtype=np.float32)
    out = esac_infer_cpp(coords, pixels, 500.0, (80.0, 60.0), n_hyps=8,
                         return_scores=True)
    assert out["n_valid"] == 0
    assert (out["scores"] == -1.0).all()


# ---- training-mode parity (SURVEY.md §2 #3-4: the extension serves training)
#
# Correspondence-set INJECTION (esac_train_loss(idx=...) / esac_cpp_train's
# idx argument) runs both backends on identical hypothesis sets, so training
# parity is tested ELEMENTWISE, not statistically.  Rows whose P3P root
# choice flips between float32 (jax production dtype) and float64 (cpp) are
# expected on ambiguous minimal sets; thresholds below budget for them.

F4 = 525.0 / 4.0
C4 = (80.0, 60.0)
TRAIN_KW = dict(height=120, width=160, f=F4, c=C4)


def _train_fixture(noise, seed, n_hyps, dtype=jnp.float32):
    from esac_tpu.ransac.sampling import sample_correspondence_sets_exact

    key = jax.random.key(seed)
    frame = make_correspondence_frame(key, noise=noise, **TRAIN_KW)
    co = jnp.asarray(frame["coords"], dtype)
    px = jnp.asarray(frame["pixels"], dtype)
    idx = sample_correspondence_sets_exact(
        jax.random.fold_in(key, 7), n_hyps, co.shape[0]
    )
    R_gt = rodrigues(jnp.asarray(frame["rvec"], dtype))
    t_gt = jnp.asarray(frame["tvec"], dtype)
    return co, px, idx, R_gt, t_gt


@pytest.mark.parametrize("noise,seed", [(0.003, 0), (0.01, 11)])
def test_train_forward_parity(noise, seed):
    """Same hypothesis sets -> per-expert expected losses agree within 10%
    and >=80% of per-hypothesis scores agree elementwise."""
    from esac_tpu.backends import esac_train_cpp
    from esac_tpu.ransac import esac_train_loss

    co, px, idx, R_gt, t_gt = _train_fixture(noise, seed, n_hyps=64)
    cfg = RansacConfig(n_hyps=64, train_refine_iters=2)
    _, aux = esac_train_loss(
        jax.random.key(1), jnp.zeros(1), co[None], px, jnp.float32(F4),
        jnp.asarray(C4), R_gt, t_gt, cfg, "dense", idx[None]
    )
    out = esac_train_cpp(
        np.asarray(co)[None], np.asarray(px), np.asarray(idx)[None], F4, C4,
        np.asarray(R_gt), np.asarray(t_gt), alpha=cfg.alpha,
        train_refine_iters=2, want_grad=False,
    )
    sj, sc = np.asarray(aux["scores"])[0], out["scores"][0]
    # Scale-aware agreement: a score is a sum of ~n_cells sigmoid terms (this
    # fixture: 300 cells, near-perfect hypotheses score ~296), so f32-vs-f64
    # drift through P3P + projection moves it proportionally to its magnitude
    # — measured up to ~1.3% relative on the 0.003-noise fixture with NO root
    # flip involved (the pose agrees; only low-order bits of the projection
    # differ).  An absolute 0.5 window on a ~296 score is a 0.17% relative
    # demand, tighter than f32 conditioning supports; rows that differ in
    # BOTH senses (e.g. 0 vs 296) are genuine f32/f64 P3P root-choice flips,
    # which the >=80% budget below exists for (measured: 12.5% flips here).
    d = np.abs(sj - sc)
    agree = (d < 0.5) | (d / np.maximum(np.abs(sc), 1.0) < 0.01)
    assert agree.mean() >= 0.8
    Ej = float(aux["per_expert_loss"][0])
    Ec = float(out["expert_losses"][0])
    assert abs(Ej - Ec) / max(Ec, 1e-6) < 0.10


# Tier-1 budget (TODO item 9, ISSUE 17): ~17s; tier-1 keeps the forward
# parity sweep and the pose-agreement pins, full `pytest tests/` keeps this.
@pytest.mark.slow
def test_train_gradient_parity_x64():
    """Matched precision (jax x64) + refine=0: the cpp backward (analytic
    selection path + central differences through the solve, the reference's
    own technique) must agree in direction and magnitude with jax autodiff."""
    from esac_tpu.backends import esac_train_cpp
    from esac_tpu.ransac import esac_train_loss

    # jax dropped the top-level enable_x64 alias in the drift window; the
    # context manager lives under jax.experimental.
    from jax.experimental import enable_x64

    with enable_x64(True):
        co, px, idx, R_gt, t_gt = _train_fixture(
            0.01, 3, n_hyps=48, dtype=jnp.float64
        )
        cfg = RansacConfig(n_hyps=48, train_refine_iters=0)
        logits = jnp.zeros(1, jnp.float64)
        f64, c64 = jnp.float64(F4), jnp.asarray(C4, jnp.float64)

        def lossf(ca):
            return esac_train_loss(
                jax.random.key(1), logits, ca, px, f64, c64, R_gt, t_gt,
                cfg, "dense", idx[None]
            )[0]

        gj = np.asarray(jax.grad(lossf)(co[None]))
        out = esac_train_cpp(
            np.asarray(co)[None], np.asarray(px), np.asarray(idx)[None], F4,
            C4, np.asarray(R_gt), np.asarray(t_gt), alpha=cfg.alpha,
            train_refine_iters=0,
        )
    gc = out["grad_coords"]
    cos = (gj * gc).sum() / (np.linalg.norm(gj) * np.linalg.norm(gc) + 1e-12)
    assert cos > 0.95
    ratio = np.linalg.norm(gc) / (np.linalg.norm(gj) + 1e-12)
    assert 0.75 < ratio < 1.3


def test_train_bridge_gating_gradient_direction():
    """Through the custom_vjp bridge, the gating gradient must favor the
    expert whose coordinate map is correct (dense-mode exactness)."""
    from esac_tpu.backends.train_bridge import make_cpp_expert_losses
    from esac_tpu.ransac.sampling import sample_correspondence_sets

    co, px, idx0, R_gt, t_gt = _train_fixture(0.01, 0, n_hyps=32)
    n = co.shape[0]
    bad = jax.random.uniform(jax.random.key(9), (n, 3), maxval=5.0)
    coords_all = jnp.stack([bad, co])  # expert 1 is correct
    cfg = RansacConfig(n_hyps=32, train_refine_iters=1)
    fn = make_cpp_expert_losses(px, F4, C4, cfg)
    idx = sample_correspondence_sets(jax.random.key(2), 64, n).reshape(2, 32, 4)

    def loss(logits):
        E = fn(coords_all, R_gt, t_gt, idx)
        return jnp.sum(jax.nn.softmax(logits) * E)

    g = jax.grad(loss)(jnp.zeros(2))
    assert float(g[1]) < 0 < float(g[0])  # push mass toward the correct expert


# ---- gating-faithful cpp allocation (SURVEY.md §0 step 1)


def _expert_maps(key, M, correct, noise=0.01):
    frame = make_correspondence_frame(key, noise=noise, **TRAIN_KW)
    n = frame["coords"].shape[0]
    maps = []
    for m in range(M):
        if m == correct:
            maps.append(np.asarray(frame["coords"]))
        else:
            maps.append(np.asarray(
                jax.random.uniform(jax.random.fold_in(key, m), (n, 3), maxval=5.0)
            ))
    return np.stack(maps), frame


def test_cpp_gated_allocation_tracks_gating_mass():
    from esac_tpu.backends import esac_infer_gated_cpp

    coords_all, frame = _expert_maps(jax.random.key(0), 4, correct=1)
    gating = np.array([0.6, 0.3, 0.1, 0.0], np.float32)
    out = esac_infer_gated_cpp(
        coords_all, np.asarray(frame["pixels"]), gating, F4, C4,
        n_hyps=1000, seed=0,
    )
    counts = out["counts"]
    assert counts.sum() == 1000
    assert counts[3] == 0                        # zero-mass expert never drawn
    np.testing.assert_allclose(counts[:3] / 1000.0, gating[:3], atol=0.06)


def test_cpp_gated_finds_correct_expert_with_mass():
    from esac_tpu.backends import esac_infer_gated_cpp

    coords_all, frame = _expert_maps(jax.random.key(1), 4, correct=2)
    gating = np.array([0.25, 0.25, 0.25, 0.25], np.float32)
    out = esac_infer_gated_cpp(
        coords_all, np.asarray(frame["pixels"]), gating, F4, C4, n_hyps=256,
    )
    assert out["expert"] == 2
    r_err, t_err = pose_errors(
        jnp.asarray(out["R"], jnp.float32), jnp.asarray(out["t"], jnp.float32),
        rodrigues(frame["rvec"]), frame["tvec"],
    )
    assert float(r_err) < 5.0 and float(t_err) < 0.05


def test_cpp_gated_miss_fails_frame_like_topk():
    """True expert at zero gating mass -> no hypotheses on the right map ->
    bad pose, exactly the jax esac_infer_topk miss semantics."""
    from esac_tpu.backends import esac_infer_gated_cpp

    coords_all, frame = _expert_maps(jax.random.key(2), 4, correct=3)
    gating = np.array([0.5, 0.3, 0.2, 0.0], np.float32)
    out = esac_infer_gated_cpp(
        coords_all, np.asarray(frame["pixels"]), gating, F4, C4, n_hyps=256,
    )
    assert out["counts"][3] == 0
    assert out["expert"] != 3
    r_err, t_err = pose_errors(
        jnp.asarray(out["R"], jnp.float32), jnp.asarray(out["t"], jnp.float32),
        rodrigues(frame["rvec"]), frame["tvec"],
    )
    assert float(r_err) > 5.0 or float(t_err) > 0.05
