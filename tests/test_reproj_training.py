"""Stage-1 reprojection-loss mode: the outdoor/no-depth-GT path.

SURVEY.md §0 stage 1 / §2 #9: when a scene has no depth GT (Aachen), the
reference initializes experts against heuristic constant-depth targets and
trains with a (clamped) reprojection loss against the GT pose.
"""

import subprocess
import sys
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from esac_tpu.data import CAMERA_F, make_correspondence_frame
from esac_tpu.data.synthetic import output_pixel_grid
from esac_tpu.geometry import backproject_at_depth, rodrigues
from esac_tpu.train import reprojection_loss

REPO = pathlib.Path(__file__).resolve().parent.parent


def test_backproject_at_depth_roundtrip():
    """Back-projected points must reproject to their pixels with the given
    camera depth under the same pose."""
    from esac_tpu.geometry import project, transform_points

    rvec = jnp.asarray([0.2, -0.1, 0.3])
    tvec = jnp.asarray([0.5, -0.2, 1.0])
    R = rodrigues(rvec)
    pixels = output_pixel_grid(96, 128, 8)
    f = jnp.float32(100.0)
    c = jnp.asarray([64.0, 48.0])
    X = backproject_at_depth(R, tvec, pixels, f, c, 4.0)
    Y = transform_points(R, tvec, X)
    np.testing.assert_allclose(np.asarray(Y[:, 2]), 4.0, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(project(Y, f, c)),
                               np.asarray(pixels), atol=1e-3)


def test_reprojection_loss_zero_at_gt():
    """GT scene coordinates have (near) zero reprojection loss; perturbed
    ones have more, and the gradient is finite and nonzero."""
    frame = make_correspondence_frame(jax.random.key(0), noise=0.0,
                                      outlier_frac=0.0)
    f = jnp.float32(CAMERA_F)
    c = jnp.asarray([320.0, 240.0])
    pred = frame["coords"][None]
    rv, tv = frame["rvec"][None], frame["tvec"][None]
    l0 = reprojection_loss(pred, rv, tv, frame["pixels"], f, c)
    l1 = reprojection_loss(pred + 0.05, rv, tv, frame["pixels"], f, c)
    assert float(l0) < 0.5 < float(l1)
    g = jax.grad(lambda p: reprojection_loss(p, rv, tv, frame["pixels"], f, c))(pred)
    assert jnp.all(jnp.isfinite(g)) and jnp.any(g != 0)


# ~37s CLI training whose final checkpoint read needed the orbax metadata
# fix (FAILURE at seed); too expensive for the 870s tier-1 budget on this
# 1-core container — `pytest tests/` still runs it.
@pytest.mark.slow
def test_cli_reproj_mode_trains(tmp_path):
    """train_expert --loss reproj end-to-end on a synthetic scene (forcing
    the no-coords path); loss decreases and the checkpoint records the mode."""
    from esac_tpu.utils.checkpoint import load_checkpoint

    r = subprocess.run(
        [sys.executable, str(REPO / "train_expert.py"), "synth0", "--cpu",
         "--size", "test", "--batch", "2", "--iterations", "40",
         "--learningrate", "1e-3", "--loss", "reproj", "--init-iters", "20",
         "--init-depth", "4.0", "--output", str(tmp_path / "ck")],
        capture_output=True, text=True, cwd=REPO, timeout=900,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "init L1" in r.stdout and "reproj px" in r.stdout
    _, cfg = load_checkpoint(tmp_path / "ck")
    assert cfg["loss_mode"] == "reproj"
    assert np.isfinite(cfg["final_loss"])


def test_reprojection_loss_gradient_above_clamp():
    """The clamp is logarithmic, not a hard min: cells far above clamp_px —
    including behind-camera cells (err+1000) — must keep a nonzero gradient,
    or a cold start (--init-iters 0) stalls with most cells >clamp."""
    frame = make_correspondence_frame(jax.random.key(2), noise=0.0,
                                      outlier_frac=0.0)
    c = jnp.asarray([320.0, 240.0])
    rv, tv = frame["rvec"][None], frame["tvec"][None]
    fs = jnp.float32(CAMERA_F)
    # Every prediction collapsed far behind the camera: worst-case regime.
    pred = jnp.full_like(frame["coords"], -50.0)[None]
    loss, g = jax.value_and_grad(
        lambda p: reprojection_loss(p, rv, tv, frame["pixels"], fs, c,
                                    clamp_px=100.0)
    )(pred)
    assert jnp.isfinite(loss) and float(loss) > 100.0  # damped, not capped
    assert jnp.all(jnp.isfinite(g))
    # Nonzero gradient for (essentially) every cell, not just a lucky few.
    per_cell = jnp.abs(g).sum(-1).ravel()
    assert float(jnp.mean(per_cell > 0)) > 0.99


def test_reprojection_loss_per_frame_focals():
    """Outdoor batches mix cameras: reprojection_loss must honor per-frame
    focal lengths, not broadcast frame 0's."""
    frame = make_correspondence_frame(jax.random.key(1), noise=0.0,
                                      outlier_frac=0.0)
    c = jnp.asarray([320.0, 240.0])
    pred = jnp.stack([frame["coords"], frame["coords"]])
    rv = jnp.stack([frame["rvec"]] * 2)
    tv = jnp.stack([frame["tvec"]] * 2)
    px = frame["pixels"]
    # Frame 1 rendered with CAMERA_F but scored at half focal: large error.
    fs = jnp.asarray([CAMERA_F, CAMERA_F / 2.0])
    mixed = reprojection_loss(pred, rv, tv, px, fs, c)
    uniform = reprojection_loss(pred, rv, tv, px, jnp.float32(CAMERA_F), c)
    assert float(uniform) < 0.5          # both frames consistent
    assert float(mixed) > float(uniform) + 1.0  # frame 1's focal mattered


# ~33s; orbax-drift FAILURE at seed — same budget reasoning as
# test_cli_reproj_mode_trains.
@pytest.mark.slow
def test_cli_auto_mode_on_diskscene_without_depth(tmp_path):
    """An on-disk scene with poses but NO depth/init (the Aachen layout
    after setup) auto-selects reprojection mode and trains."""
    from PIL import Image

    from esac_tpu.utils.checkpoint import load_checkpoint

    scene = tmp_path / "data" / "outdoor" / "training"
    for sub in ("rgb", "poses", "calibration"):
        (scene / sub).mkdir(parents=True)
    rng = np.random.default_rng(0)
    for i in range(3):
        Image.fromarray(
            rng.integers(0, 255, (48, 64, 3), dtype=np.uint8), "RGB"
        ).save(scene / "rgb" / f"f{i}.png")
        T = np.eye(4)
        T[:3, 3] = [0.1 * i, 0.0, -2.0]  # camera-to-scene
        np.savetxt(scene / "poses" / f"f{i}.txt", T)
        np.savetxt(scene / "calibration" / f"f{i}.txt", [60.0])
    r = subprocess.run(
        [sys.executable, str(REPO / "train_expert.py"), "outdoor", "--cpu",
         "--root", str(tmp_path / "data"), "--size", "test", "--batch", "2",
         "--iterations", "6", "--init-iters", "3",
         "--output", str(tmp_path / "ck")],
        capture_output=True, text=True, cwd=REPO, timeout=900,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert "init L1" in r.stdout
    assert load_checkpoint(tmp_path / "ck")[1]["loss_mode"] == "reproj"


# ~56s stop/resume; orbax-drift FAILURE at seed — same budget reasoning
# as test_cli_reproj_mode_trains.
@pytest.mark.slow
def test_cli_reproj_resume_inside_bootstrap(tmp_path):
    """Stop during the heuristic-bootstrap phase and resume: the resumed
    process must rebuild the bootstrap targets (heur_d is allocated only
    when init_iters > start_it) and finish both phases."""
    cmd = [sys.executable, str(REPO / "train_expert.py"), "synth0", "--cpu",
           "--size", "test", "--batch", "2", "--iterations", "24",
           "--learningrate", "1e-3", "--loss", "reproj", "--init-iters", "12",
           "--output", str(tmp_path / "ck")]
    r1 = subprocess.run(cmd + ["--stop-after", "6"], capture_output=True,
                        text=True, cwd=REPO, timeout=900)
    assert r1.returncode == 0, r1.stdout + r1.stderr
    r2 = subprocess.run(cmd + ["--resume"], capture_output=True, text=True,
                        cwd=REPO, timeout=900)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "resumed" in r2.stdout and "init L1" in r2.stdout
    assert "reproj px" in r2.stdout  # second phase reached after resume


def test_cli_rejects_reproj_plus_augment():
    r = subprocess.run(
        [sys.executable, str(REPO / "train_expert.py"), "synth0", "--cpu",
         "--size", "test", "--iterations", "2", "--loss", "reproj",
         "--augment", "--output", "/tmp/never"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert r.returncode != 0 and "augment" in r.stderr
