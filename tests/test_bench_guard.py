"""Wedge-safety tests for bench.py's device-measurement guard.

The invariant under test (CLAUDE.md hazard + VERDICT r1 weak #1): no code
path in bench.py may ever kill a device-touching child.  These tests drive
``relay_alive``/``measure_on_device`` against fake phase files and a stubbed
spawner, and assert the decisions AND that nothing was signalled.
"""

import json
import time

import bench


class _FakeChild:
    """Stands in for Popen; records any kill/terminate attempt."""

    def __init__(self):
        self.killed = False

    def poll(self):
        return None  # "still running"

    def kill(self):  # pragma: no cover - the test fails if this runs
        self.killed = True

    terminate = kill


def _write_phase(phase, t=None, pid=None):
    import os

    bench._PROBE_FILE.write_text(
        json.dumps({
            "phase": phase,
            "t": t if t is not None else time.time(),
            # Default to a live pid (our own): an unresolved probe only
            # counts as unresolved while its process exists.
            "pid": pid if pid is not None else os.getpid(),
        })
    )


def test_stale_stuck_probe_means_wedged(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_FILE", tmp_path / "probe.json")
    _write_phase("backend_init", t=time.time() - 1000)
    alive, reason = bench.relay_alive(deadline_s=5)
    assert not alive and "stuck" in reason


def test_recent_ok_probe_is_alive(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_FILE", tmp_path / "probe.json")
    _write_phase("ok")
    alive, reason = bench.relay_alive(deadline_s=5)
    assert alive


def test_unresolved_probe_blocks_new_probe_launch(tmp_path, monkeypatch):
    """A young unresolved probe must be waited on, never duplicated."""
    monkeypatch.setattr(bench, "_PROBE_FILE", tmp_path / "probe.json")
    launched = []
    monkeypatch.setattr(bench, "_spawn_orphan", lambda *a, **k: launched.append(a) or _FakeChild())
    _write_phase("backend_init", t=time.time() - 1)
    alive, reason = bench.relay_alive(deadline_s=3)
    assert not alive
    assert launched == []  # did NOT start a second device-touching process


def test_dead_probe_pid_clears_file_and_relaunches(tmp_path, monkeypatch):
    """A stuck phase file whose process is gone must not disable device
    measurement forever: nothing is awaiting the device, so a fresh probe
    may be launched (r2 code-review finding)."""
    monkeypatch.setattr(bench, "_PROBE_FILE", tmp_path / "probe.json")
    launched = []

    def fake_spawn(argv, log):
        launched.append(argv)
        _write_phase("ok")
        return _FakeChild()

    monkeypatch.setattr(bench, "_spawn_orphan", fake_spawn)
    _write_phase("backend_init", t=time.time() - 9999, pid=2**22 + 12345)
    alive, _ = bench.relay_alive(deadline_s=5)
    assert alive and len(launched) == 1


def test_probe_launched_when_no_phase_file(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_FILE", tmp_path / "probe.json")
    launched = []

    def fake_spawn(argv, log):
        launched.append(argv)
        _write_phase("ok")  # probe succeeds instantly
        return _FakeChild()

    monkeypatch.setattr(bench, "_spawn_orphan", fake_spawn)
    alive, _ = bench.relay_alive(deadline_s=5)
    assert alive and len(launched) == 1
    assert "tpu_probe" in " ".join(launched[0])


def test_measurement_deadline_orphans_child(tmp_path, monkeypatch):
    """On deadline the child is abandoned — poll() only, no kill."""
    monkeypatch.setattr(bench, "_PROBE_FILE", tmp_path / "probe.json")
    monkeypatch.setattr(bench, "_RESULT_FILE", tmp_path / "result.json")
    _write_phase("ok")
    child = _FakeChild()
    monkeypatch.setattr(bench, "_spawn_orphan", lambda *a, **k: child)
    t0 = time.time()
    res = bench.measure_on_device({}, deadline_s=3)
    assert res is None
    assert time.time() - t0 < 30
    assert not child.killed


def test_measurement_result_read_from_file(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_FILE", tmp_path / "probe.json")
    monkeypatch.setattr(bench, "_RESULT_FILE", tmp_path / "result.json")
    _write_phase("ok")

    def fake_spawn(argv, log):
        (tmp_path / "result.json").write_text(
            json.dumps({"rate": 123.0, "platform": "tpu", "device_kind": "fake"})
        )
        return _FakeChild()

    monkeypatch.setattr(bench, "_spawn_orphan", fake_spawn)
    res = bench.measure_on_device({}, deadline_s=5)
    assert res["rate"] == 123.0
    assert res["platform"] == "tpu"


def test_no_kill_calls_anywhere_in_bench_source():
    """Static belt-and-braces: bench.py must not reference kill/terminate or
    subprocess timeouts (the r1 guard's exact failure mode)."""
    import pathlib

    src = (pathlib.Path(bench.__file__)).read_text()
    for banned in (".kill(", ".terminate(", "timeout="):
        assert banned not in src, f"bench.py contains {banned!r}"


import os

import pytest


@pytest.fixture(autouse=True)
def _isolated_repo(tmp_path, monkeypatch):
    """Point bench's sentinel paths at tmp_path so these tests neither see
    nor disturb a real .tpu_busy written by a sanctioned TPU job (the
    chip-recovery runbook may own the chip while the suite runs)."""
    monkeypatch.setattr(bench, "_REPO", tmp_path)
    yield


def test_busy_sentinel_live_owner_waits_then_cpu_fallback(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_FILE", tmp_path / "probe.json")
    _write_phase("ok")
    (tmp_path / ".tpu_busy").write_text(str(os.getpid()))  # us: alive forever
    t0 = time.time()
    res = bench.measure_on_device({}, deadline_s=2)
    assert res is None  # fell back without deleting the live owner's file
    assert (tmp_path / ".tpu_busy").exists()
    assert time.time() - t0 >= 2


def test_busy_sentinel_dead_owner_is_cleared(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_FILE", tmp_path / "probe.json")
    monkeypatch.setattr(bench, "_RESULT_FILE", tmp_path / "result.json")
    _write_phase("ok")
    # A pid that cannot exist (pid_max is far below 2**22 reads here).
    (tmp_path / ".tpu_busy").write_text("4194304")

    def fake_spawn(argv, log):
        (tmp_path / "result.json").write_text(
            json.dumps({"rate": 1.0, "platform": "tpu", "device_kind": "fake"})
        )
        return _FakeChild()

    monkeypatch.setattr(bench, "_spawn_orphan", fake_spawn)
    res = bench.measure_on_device({}, deadline_s=5)
    assert res is not None
    assert not (tmp_path / ".tpu_busy").exists()


def test_busy_sentinel_rewritten_by_new_owner_not_deleted(tmp_path, monkeypatch):
    """The read-then-unlink race: if a NEW live owner rewrites .tpu_busy
    after we judged the old contents stale, the unlink must not happen."""
    busy = tmp_path / ".tpu_busy"
    busy.write_text("4194304")  # dead owner

    calls = {"n": 0}
    real_read = type(busy).read_text

    def racing_read(self, *a, **k):
        out = real_read(self, *a, **k)
        if calls["n"] == 0 and self.name == ".tpu_busy":
            # Between the wait-loop's read and the unlink re-check, a new
            # owner (alive: our own pid) takes the sentinel.
            busy.write_text(str(os.getpid()))
        calls["n"] += 1
        return out

    monkeypatch.setattr(type(busy), "read_text", racing_read)
    monkeypatch.setattr(bench, "_PROBE_FILE", tmp_path / "probe.json")
    _write_phase("ok")
    res = bench.measure_on_device({}, deadline_s=2)
    assert res is None  # waited on the new owner, then CPU fallback
    assert busy.exists() and busy.read_text() == str(os.getpid())


def test_busy_sentinel_unparsable_ages_out_after_a_day(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_FILE", tmp_path / "probe.json")
    monkeypatch.setattr(bench, "_RESULT_FILE", tmp_path / "result.json")
    _write_phase("ok")
    busy = tmp_path / ".tpu_busy"
    busy.write_text("not a pid")
    day_ago = time.time() - 25 * 3600
    os.utime(busy, (day_ago, day_ago))

    def fake_spawn(argv, log):
        (tmp_path / "result.json").write_text(
            json.dumps({"rate": 1.0, "platform": "tpu", "device_kind": "fake"})
        )
        return _FakeChild()

    monkeypatch.setattr(bench, "_spawn_orphan", fake_spawn)
    res = bench.measure_on_device({}, deadline_s=5)
    assert res is not None and not busy.exists()

    # Young unparsable sentinel still waits (ambiguity is never deleted).
    busy.write_text("not a pid")
    t0 = time.time()
    assert bench.measure_on_device({}, deadline_s=2) is None
    assert busy.exists() and time.time() - t0 >= 2


def _proc_state(pid):
    with open(f"/proc/{pid}/stat") as fh:
        return fh.read().rsplit(") ", 1)[1].split()[0]


def test_pause_pipelines_stops_and_resumes_pidfile_group(tmp_path, monkeypatch):
    """VERDICT r3 weak #1/#7: bench must quiesce the repo's own background
    queues for the measurement window — and always hand the CPU back."""
    import subprocess

    monkeypatch.setattr(bench, "_REPO", tmp_path)
    monkeypatch.setattr(bench, "_orphan_trainer_pgids", lambda: set())
    child = subprocess.Popen(
        ["sleep", "60"], start_new_session=True,
        stdin=subprocess.DEVNULL, stdout=subprocess.DEVNULL,
    )
    try:
        (tmp_path / ".pipeline.pid").write_text(f"{child.pid}\n")
        stopped, load_before = bench._pause_pipelines()
        assert stopped == [os.getpgid(child.pid)]
        deadline = time.time() + 5
        while _proc_state(child.pid) != "T" and time.time() < deadline:
            time.sleep(0.05)
        assert _proc_state(child.pid) == "T"  # SIGSTOPped
        assert len(load_before) == 3
        bench._resume_pipelines(stopped)
        while _proc_state(child.pid) == "T" and time.time() < deadline:
            time.sleep(0.05)
        assert _proc_state(child.pid) in ("S", "R")
        blk = bench._contention_block(stopped, load_before)
        assert blk["paused_pipeline_pgids"] == stopped
    finally:
        child.kill()
        child.wait()


def test_pause_pipelines_never_stops_own_group(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_REPO", tmp_path)
    monkeypatch.setattr(bench, "_orphan_trainer_pgids", lambda: set())
    (tmp_path / ".pipeline.pid").write_text(f"{os.getpid()}\n")
    stopped, _ = bench._pause_pipelines()
    assert stopped == []


def test_pause_pipelines_ignores_dead_and_garbage_pidfile(tmp_path, monkeypatch):
    monkeypatch.setattr(bench, "_REPO", tmp_path)
    monkeypatch.setattr(bench, "_orphan_trainer_pgids", lambda: set())
    (tmp_path / ".pipeline.pid").write_text("999999999 not-a-pid\n")
    stopped, _ = bench._pause_pipelines()
    assert stopped == []


def test_pause_pipelines_skips_group_with_non_cpu_python(tmp_path, monkeypatch):
    """A pidfile group containing a python process WITHOUT an explicit --cpu
    flag could be a TPU-relay client: bench must refuse to SIGSTOP it
    (conservative: unpaused = contention, paused relay holder = stall)."""
    import subprocess
    import sys

    monkeypatch.setattr(bench, "_REPO", tmp_path)
    monkeypatch.setattr(bench, "_orphan_trainer_pgids", lambda: set())
    child = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(60)"],
        start_new_session=True,
        stdin=subprocess.DEVNULL, stdout=subprocess.DEVNULL,
    )
    try:
        (tmp_path / ".pipeline.pid").write_text(f"{child.pid}\n")
        stopped, _ = bench._pause_pipelines()
        assert stopped == []
        assert _proc_state(child.pid) in ("S", "R")  # untouched
    finally:
        child.kill()
        child.wait()


def test_pause_pipelines_skips_group_with_unjudgeable_cmdline(tmp_path, monkeypatch):
    """Regression (PR 3): a process whose /proc cmdline STAYS empty — a
    zombie here; the same read a child gives between clone and execve —
    cannot be judged CPU-only, and an about-to-exec child may become a
    non---cpu python, so bench must refuse to pause the group.  Before the
    fix, an empty cmdline was invisible to the python-without---cpu check
    and the group was judged pausable."""
    import subprocess

    monkeypatch.setattr(bench, "_REPO", tmp_path)
    monkeypatch.setattr(bench, "_orphan_trainer_pgids", lambda: set())
    child = subprocess.Popen(
        ["sleep", "0"], start_new_session=True,
        stdin=subprocess.DEVNULL, stdout=subprocess.DEVNULL,
    )
    try:
        # Let it exit WITHOUT reaping: the zombie keeps its pid/pgid but
        # its cmdline reads empty forever — the permanently-unjudgeable
        # case (also exercises _pgid_cpu_only's re-read grace loop).
        deadline = time.time() + 10
        while _proc_state(child.pid) != "Z" and time.time() < deadline:
            time.sleep(0.01)
        assert _proc_state(child.pid) == "Z"
        (tmp_path / ".pipeline.pid").write_text(f"{child.pid}\n")
        stopped, _ = bench._pause_pipelines()
        assert stopped == []
    finally:
        child.wait()


def test_breadcrumb_dead_owner_resumed_and_cleaned(tmp_path, monkeypatch):
    """ADVICE r4: a bench SIGKILLed mid-pause must not freeze the queues
    forever — the next invocation resumes pgids from the breadcrumb."""
    import os
    import signal as sig

    monkeypatch.setattr(bench, "_REPO", tmp_path)
    sent = []
    monkeypatch.setattr(bench.os, "killpg",
                        lambda pg, s: sent.append((pg, s)))
    # Owner pid 999999 is dead -> resume listed pgids, remove the file.
    crumb = tmp_path / ".bench_paused.pgids"
    crumb.write_text("owner=999999 12345 67890\n")
    bench._resume_stale_breadcrumb()
    assert sent == [(12345, sig.SIGCONT), (67890, sig.SIGCONT)]
    assert not crumb.exists()


def test_breadcrumb_live_owner_left_alone(tmp_path, monkeypatch):
    """A breadcrumb owned by a still-running bench is a LIVE pause: resuming
    would un-quiet a measurement in progress (r5 review finding)."""
    import os

    monkeypatch.setattr(bench, "_REPO", tmp_path)
    sent = []
    monkeypatch.setattr(bench.os, "killpg",
                        lambda pg, s: sent.append((pg, s)))
    crumb = tmp_path / ".bench_paused.pgids"
    # A DIFFERENT live pid owns the pause (PID 1 always exists).
    crumb.write_text("owner=1 12345\n")
    bench._resume_stale_breadcrumb()
    assert sent == [] and crumb.exists()


# ---------------- loadtest driver contract (ISSUE 7) ----------------

def _canned_loadtest():
    """Minimal-but-complete loadtest payload: the schema the driver and
    the committed artifact rely on."""
    def point(mult, served, shed):
        n = served + shed
        return {
            "offered_x_capacity": mult,
            "offered_rps": 100.0 * mult,
            "offered": n,
            "offered_rps_target": 100.0 * mult,
            "offered_rps_achieved": 99.0 * mult,
            "outcomes": {"served": served, "degraded": 0, "shed": shed,
                         "expired": 0, "failed": 0, "lost": 0},
            "goodput_ratio": served / n,
            "served_rps": 90.0,
            "sustained_hyps_per_s": 1440.0,
            "p50_ms": 5.0,
            "p99_ms": 12.0,
            "span_s": 1.0,
        }

    def leg(program, route_k, bucket, knee):
        return {
            "program": program, "route_k": route_k, "frame_bucket": bucket,
            "closed_loop_dispatch_ms": 2.0,
            "closed_loop_capacity_rps": 100.0,
            "deadline_ms": 300.0, "compiled_programs": 1,
            "points": [point(0.5, 50, 0), point(2.0, 60, 40)],
            "knee_offered_rps": 50.0 if knee else None,
            "knee_sustained_hyps_per_s": knee,
        }

    return {
        "num_experts": 4, "hw": [24, 24], "hyps_per_request": 16,
        "offered_mults": [0.5, 2.0], "open_loop_seconds_per_point": 2.5,
        "legs": [
            leg("dense", None, 2, 800.0),
            leg("dense", None, 8, 1440.0),
            leg("routed_k2", 2, 2, 700.0),
            leg("routed_k2", 2, 8, 1200.0),
        ],
        "note": "canned",
    }


def test_loadtest_main_emits_one_json_line_and_artifact(tmp_path, monkeypatch, capsys):
    """The driver contract: ONE parseable JSON line on stdout, the
    headline value from the dense/largest-bucket leg's knee, and the
    .serve_loadtest.json artifact with platform + recorded_at."""
    monkeypatch.setattr(bench, "_LOADTEST_FILE", tmp_path / "loadtest.json")
    monkeypatch.setattr(
        bench, "measure_on_device",
        lambda *a, **k: {"loadtest": _canned_loadtest(), "platform": "tpu",
                         "device_kind": "fake-tpu"},
    )
    bench._loadtest_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1, f"expected ONE JSON line, got {len(lines)}"
    out = json.loads(lines[0])
    assert out["metric"] == "serve_loadtest_knee_sustained_hyps_per_s"
    assert out["value"] == 1440.0  # dense, frame_bucket 8
    assert out["unit"] == "hyps/s"
    assert "vs_baseline" in out
    assert out["device_kind"] == "fake-tpu"
    assert out["knee_offered_rps_dense_big_bucket"] == 50.0
    assert "contention" in out
    artifact = json.loads((tmp_path / "loadtest.json").read_text())
    assert artifact["platform"] == "tpu"
    assert "recorded_at" in artifact
    assert len(artifact["loadtest"]["legs"]) == 4


def test_loadtest_cpu_fallback_carries_provenance(tmp_path, monkeypatch, capsys):
    """Relay wedged -> the sweep measures on CPU and SAYS so: note field
    on the JSON line, platform "cpu" in the artifact."""
    monkeypatch.setattr(bench, "_LOADTEST_FILE", tmp_path / "loadtest.json")
    monkeypatch.setattr(bench, "measure_on_device", lambda *a, **k: None)
    monkeypatch.setattr(bench, "_measure_loadtest",
                        lambda *a, **k: _canned_loadtest())
    bench._loadtest_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert "CPU" in out["note"] or "cpu" in out["note"]
    artifact = json.loads((tmp_path / "loadtest.json").read_text())
    assert artifact["platform"] == "cpu"
    assert artifact["note"] == out["note"]


def test_loadtest_artifact_schema_outcome_accounting():
    """The committed .serve_loadtest.json (when present) satisfies the
    schema the driver consumes — per-point outcome accounting sums to
    offered, every leg locates (or honestly nulls) its knee."""
    import pathlib

    path = pathlib.Path(bench.__file__).parent / ".serve_loadtest.json"
    if not path.exists():
        import pytest

        pytest.skip("no committed loadtest artifact yet")
    artifact = json.loads(path.read_text())
    for key in ("metric", "value", "unit", "platform", "recorded_at",
                "loadtest"):
        assert key in artifact, key
    legs = artifact["loadtest"]["legs"]
    assert {(l["program"], l["frame_bucket"]) for l in legs} >= {
        ("dense", 2), ("dense", 8), ("routed_k2", 2), ("routed_k2", 8),
    }
    for leg in legs:
        assert leg["compiled_programs"] == 1  # one program per (K, bucket)
        for p in leg["points"]:
            o = p["outcomes"]
            total = sum(o[k] for k in
                        ("served", "degraded", "shed", "expired", "failed",
                         "lost"))
            assert total == p["offered"], (leg["program"], p)


def test_loadtest_knee_is_longest_passing_prefix():
    """A noisy non-monotone sweep must not report a knee ABOVE a load the
    server already failed: the knee is the last point of the longest
    goodput>=0.99 prefix, not the max passing point."""
    def pt(mult, good):
        return {"offered_x_capacity": mult, "offered_rps": 100.0 * mult,
                "goodput_ratio": good}

    assert bench._loadtest_knee([])is None
    assert bench._loadtest_knee([pt(0.4, 0.9)]) is None
    monotone = [pt(0.4, 1.0), pt(0.8, 1.0), pt(1.2, 0.85), pt(2.0, 0.6)]
    assert bench._loadtest_knee(monotone)["offered_x_capacity"] == 0.8
    # Non-monotone: 0.8 failed, 1.2 "passed" by luck -> knee stays at 0.4.
    noisy = [pt(0.4, 1.0), pt(0.8, 0.958), pt(1.2, 1.0), pt(2.0, 0.6)]
    assert bench._loadtest_knee(noisy)["offered_x_capacity"] == 0.4


# ---------------- scoring driver contract (ISSUE 8) ----------------

def _canned_scoring():
    """Minimal-but-complete scoring-sweep payload: the schema the driver
    and the committed .scoring_fused.json artifact rely on."""
    def point(n_hyps, fs_rate):
        return {
            "n_hyps": n_hyps,
            "total_hyps_per_dispatch": 16 * n_hyps,
            "errmap_term_mb": round(16 * n_hyps * 4800 * 4 / 1e6, 2),
            "impls": {
                impl: {"dispatch_ms": 2.0, "hyps_per_s": rate,
                       "wall_s_spread": [0.002, 0.002, 0.002]}
                for impl, rate in (("errmap", 1000.0), ("fused", 1500.0),
                                   ("fused_select", fs_rate))
            },
            "winner_bit_identical": True,
            "fused_select_speedup_x": round(fs_rate / 1000.0, 3),
        }

    return {
        "batch_frames": 16,
        "n_cells": 4800,
        "n_hyps_sweep": [64, 1024],
        "curve": [point(64, 1100.0), point(1024, 2000.0)],
        "winner_bit_identical_all": True,
        "note": "canned",
    }


def test_scoring_main_emits_one_json_line_and_artifact(tmp_path, monkeypatch, capsys):
    """The driver contract: ONE parseable JSON line on stdout, headline
    from the largest-n_hyps fused_select leg, winner agreement surfaced,
    and the .scoring_fused.json artifact with platform + recorded_at."""
    monkeypatch.setattr(bench, "_SCORING_FILE", tmp_path / "scoring.json")
    monkeypatch.setattr(
        bench, "measure_on_device",
        lambda *a, **k: {"scoring": _canned_scoring(), "platform": "tpu",
                         "device_kind": "fake-tpu"},
    )
    bench._scoring_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1, f"expected ONE JSON line, got {len(lines)}"
    out = json.loads(lines[0])
    assert out["metric"] == "scoring_fused_select_hyps_per_s_at_1024"
    assert out["value"] == 2000.0
    assert out["unit"] == "hyps/s"
    assert "vs_baseline" in out
    assert out["fused_select_speedup_x_at_max"] == 2.0
    assert out["winner_bit_identical_all"] is True
    assert out["device_kind"] == "fake-tpu"
    assert "contention" in out
    artifact = json.loads((tmp_path / "scoring.json").read_text())
    assert artifact["platform"] == "tpu"
    assert "recorded_at" in artifact
    assert len(artifact["scoring"]["curve"]) == 2


def test_scoring_cpu_fallback_carries_provenance(tmp_path, monkeypatch, capsys):
    """Relay wedged -> the sweep measures on CPU and SAYS so: note field
    on the JSON line, platform "cpu" in the artifact."""
    monkeypatch.setattr(bench, "_SCORING_FILE", tmp_path / "scoring.json")
    monkeypatch.setattr(bench, "measure_on_device", lambda *a, **k: None)
    monkeypatch.setattr(bench, "_measure_scoring",
                        lambda *a, **k: _canned_scoring())
    bench._scoring_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert "CPU" in out["note"] or "cpu" in out["note"]
    artifact = json.loads((tmp_path / "scoring.json").read_text())
    assert artifact["platform"] == "cpu"
    assert artifact["note"] == out["note"]


def test_scoring_artifact_schema_committed():
    """The committed .scoring_fused.json satisfies the schema the driver
    consumes: a full impl matrix per point, recorded winner agreement, and
    (on a CPU record) the bit-identity acceptance actually holding."""
    import pathlib

    path = pathlib.Path(bench.__file__).parent / ".scoring_fused.json"
    if not path.exists():
        import pytest

        pytest.skip("no committed scoring artifact yet")
    artifact = json.loads(path.read_text())
    for key in ("metric", "value", "unit", "platform", "recorded_at",
                "scoring"):
        assert key in artifact, key
    sc = artifact["scoring"]
    assert sc["n_hyps_sweep"] == [p["n_hyps"] for p in sc["curve"]]
    for p in sc["curve"]:
        assert set(p["impls"]) == {"errmap", "fused", "fused_select"}
        for leg in p["impls"].values():
            assert leg["hyps_per_s"] > 0
        assert isinstance(p["winner_bit_identical"], bool)
        assert p["errmap_term_mb"] > 0
    if artifact["platform"] == "cpu":
        # On CPU fused_select runs the chunked errmap-math sibling: the
        # winner must be bit-identical at EVERY sweep point.
        assert sc["winner_bit_identical_all"] is True


# ---------------- chaos driver contract (ISSUE 9) ----------------

def _canned_chaos():
    """Minimal-but-complete chaos payload: the schema the driver and the
    committed .chaos_drill.json artifact rely on."""
    def scene(outcomes, errs=None, goodput=1.0):
        return {
            "offered": sum(outcomes.values()),
            "outcomes": outcomes,
            "error_types": errs or {},
            "sums_to_offered": True,
            "goodput": goodput,
        }

    return {
        "scenes": {"n": 4, "hw": [24, 24], "num_experts": 2, "n_hyps": 4,
                   "frame_bucket": 2},
        "closed_loop_dispatch_ms": 2.0,
        "offered_rps": 500.0, "offered_x_capacity": 0.5,
        "deadline_ms": 1500.0, "offered_per_phase": 100,
        "baseline": {"s_ok": scene({"served": 25})},
        "fault_window": {
            "per_scene": {
                "s_ok": scene({"served": 25}),
                "s_corrupt": scene({"failed": 2, "shed": 23},
                                   {"ChecksumMismatchError": 2,
                                    "LaneQuarantinedError": 23}, 0.0),
                "s_ioflaky": scene({"served": 25}),
                "s_nan": scene({"served": 25}),
            },
            "accounting_exact": True,
            "dispatcher_totals": {"offered": 100, "served": 75, "shed": 23,
                                  "expired": 0, "degraded": 0, "failed": 2,
                                  "pending": 0},
            "healthy_goodput_retention": 1.0,
        },
        "faults": {
            "corrupt_checkpoint": {
                "scene": "s_corrupt", "injected_corrupt_reads": 3,
                "typed_errors": {"ChecksumMismatchError": 2},
                "quarantined_lanes": [["s_corrupt", None]],
                "released_and_recovered": True, "recovery_latency_s": 0.05,
            },
            "transient_io": {
                "scene": "s_ioflaky", "injected_failures": 2,
                "goodput": 1.0, "retried_transparently": True,
            },
            "nan_weights": {
                "scene": "s_nan", "auto_rolled_back": True,
                "rollback_latency_s": 0.2, "active_version_after": 1,
                "garbage_frames_before_trip": 4,
                "post_rollback_bit_identical": True,
            },
        },
        "canary": {"scene": "s_ok", "fraction": 0.5,
                   "events": ["canary_start", "canary_promoted"],
                   "finalized": True, "active_version_after": 2},
        "compiled_programs": {"before_faults": 1, "after_drill": 1,
                              "hot_path_recompiles": 0},
        "fault_taxonomy": {
            "observed": {"ChecksumMismatchError->failed": 2,
                         "LaneQuarantinedError->shed": 23},
            "error_free_outcomes": {"served": 75},
            "violations": [],
            "committed_errors": 13, "committed_edges": 8,
        },
        "health_events": [],
        "note": "canned",
    }


def test_chaos_main_emits_one_json_line_and_artifact(tmp_path, monkeypatch, capsys):
    """The driver contract: ONE parseable JSON line, headline = healthy
    goodput retention, the rollback/recompile acceptance fields surfaced,
    and the .chaos_drill.json artifact with platform + recorded_at."""
    monkeypatch.setattr(bench, "_CHAOS_FILE", tmp_path / "chaos.json")
    monkeypatch.setattr(
        bench, "measure_on_device",
        lambda *a, **k: {"chaos": _canned_chaos(), "platform": "tpu",
                         "device_kind": "fake-tpu"},
    )
    bench._chaos_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1, f"expected ONE JSON line, got {len(lines)}"
    out = json.loads(lines[0])
    assert out["metric"] == "chaos_healthy_scene_goodput_retention"
    assert out["value"] == 1.0
    assert out["unit"] == "goodput_ratio"
    assert "vs_baseline" in out
    assert out["accounting_exact"] is True
    assert out["post_rollback_bit_identical"] is True
    assert out["hot_path_recompiles"] == 0
    assert out["device_kind"] == "fake-tpu"
    assert "contention" in out
    artifact = json.loads((tmp_path / "chaos.json").read_text())
    assert artifact["platform"] == "tpu"
    assert "recorded_at" in artifact
    assert artifact["chaos"]["faults"]["nan_weights"]["auto_rolled_back"]


def test_chaos_cpu_fallback_carries_provenance(tmp_path, monkeypatch, capsys):
    """Relay wedged -> the drill measures on CPU and SAYS so: note field
    on the JSON line, platform "cpu" in the artifact."""
    monkeypatch.setattr(bench, "_CHAOS_FILE", tmp_path / "chaos.json")
    monkeypatch.setattr(bench, "measure_on_device", lambda *a, **k: None)
    monkeypatch.setattr(bench, "_measure_chaos", lambda *a, **k: _canned_chaos())
    bench._chaos_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert "CPU" in out["note"] or "cpu" in out["note"]
    artifact = json.loads((tmp_path / "chaos.json").read_text())
    assert artifact["platform"] == "cpu"
    assert artifact["note"] == out["note"]


def test_chaos_artifact_schema_committed():
    """The committed .chaos_drill.json satisfies the acceptance schema:
    per-fault-class outcome accounting sums exactly to offered, healthy
    goodput retained >= 0.99 under faults, the auto-rollback served
    bit-identically with zero hot-path recompiles, and the transient-IO
    fault never surfaced as a failed request."""
    import pathlib

    path = pathlib.Path(bench.__file__).parent / ".chaos_drill.json"
    if not path.exists():
        import pytest

        pytest.skip("no committed chaos artifact yet")
    artifact = json.loads(path.read_text())
    for key in ("metric", "value", "unit", "platform", "recorded_at",
                "chaos"):
        assert key in artifact, key
    chaos = artifact["chaos"]
    for phase in ("baseline", ):
        for rec in chaos[phase].values():
            assert sum(rec["outcomes"].values()) == rec["offered"]
    fw = chaos["fault_window"]
    assert set(fw["per_scene"]) == {"s_ok", "s_corrupt", "s_ioflaky",
                                    "s_nan"}
    for rec in fw["per_scene"].values():
        assert sum(rec["outcomes"].values()) == rec["offered"], rec
        assert rec["sums_to_offered"] is True
    t = fw["dispatcher_totals"]
    assert (t["served"] + t["shed"] + t["expired"] + t["degraded"]
            + t["failed"] + t["pending"] == t["offered"])
    assert fw["accounting_exact"] is True
    assert fw["healthy_goodput_retention"] >= 0.99
    faults = chaos["faults"]
    assert faults["corrupt_checkpoint"]["typed_errors"].get(
        "ChecksumMismatchError", 0) >= 1
    assert faults["corrupt_checkpoint"]["released_and_recovered"] is True
    assert faults["transient_io"]["retried_transparently"] is True
    assert faults["nan_weights"]["auto_rolled_back"] is True
    assert faults["nan_weights"]["post_rollback_bit_identical"] is True
    assert chaos["compiled_programs"]["hot_path_recompiles"] == 0
    assert chaos["canary"]["finalized"] in (True, False)
    # graft-audit v3: the runtime lock witness rode the drill — the
    # acquisition edges the fault paths actually took are a subgraph of
    # the committed .lock_graph.json order, violation-free.
    lw = chaos["lock_witness"]
    assert lw["committed_graph_present"] is True
    assert lw["violations"] == []
    assert lw["observed_subgraph_of_committed"] is True
    assert any(k.startswith("MicroBatchDispatcher._lock->")
               for k in lw["edges_observed"]), lw["edges_observed"]
    # graft-audit v5: the runtime outcome witness rode the drill — every
    # observed error type is a committed taxonomy member and every
    # (error, outcome) pair rides a committed raise->outcome edge.
    ft = chaos["fault_taxonomy"]
    assert ft["violations"] == []
    assert ft["observed"], "fault window produced no typed errors?"
    assert ft["committed_errors"] >= 13
    assert ft["committed_edges"] >= 1


def test_all_mode_mains_share_the_wedge_safe_scaffold(monkeypatch):
    """TODO item 6 (ISSUE 9 satellite): every bench mode routes through
    the ONE _driver_main scaffold — a wedge-safety or provenance fix
    cannot silently miss a mode anymore."""
    calls = []

    def spy(stopped, load_before, **kw):
        calls.append((kw["key"], kw["what"]))
        assert callable(kw["measure_cpu"]) and callable(kw["headline"])
        assert str(kw["artifact_path"]).endswith(".json")

    monkeypatch.setattr(bench, "_driver_main", spy)
    for main in (bench._serve_main, bench._registry_main,
                 bench._routed_main, bench._loadtest_main,
                 bench._scoring_main, bench._chaos_main,
                 bench._obs_main, bench._prefetch_main,
                 bench._fleet_main, bench._hostpath_main,
                 bench._city_main, bench._sessions_main):
        main([], [0.0, 0.0, 0.0])
    assert [c[0] for c in calls] == [
        "serve", "registry", "routed", "loadtest", "scoring", "chaos",
        "obs", "prefetch", "fleet", "hostpath", "city", "sessions",
    ]


# ---------------- fleet driver contract (ISSUE 14) ----------------

def _canned_fleet():
    """Minimal-but-complete fleet payload: the schema the driver and the
    committed .fleet_serve.json artifact rely on."""
    def point(mult, rps):
        return {
            "offered_x_aggregate_capacity": mult, "offered_rps": rps,
            "offered": 100, "outcomes": {"served": 100},
            "goodput_ratio": 1.0, "served_rps": rps,
            "sustained_hyps_per_s": rps * 8, "p50_ms": 5.0,
            "p99_ms": 20.0, "accounting_exact": True,
        }

    return {
        "replicas": 3,
        "scenes": {"n": 6, "hw": [24, 24], "num_experts": 2, "n_hyps": 4,
                   "frame_bucket": 2},
        "closed_loop_dispatch_ms": 2.0,
        "per_replica_capacity_rps": 1000.0,
        "deadline_ms": 4000.0, "watchdog_ms": 500.0,
        "knee_vs_replicas": [
            {"replicas": n, "points": [point(0.4, 400.0 * n)],
             "knee_offered_rps": 400.0 * n,
             "knee_sustained_hyps_per_s": 3200.0 * n}
            for n in (1, 2, 3)
        ],
        "affinity": {
            "offered_rps": 1500.0, **point(0.5, 1500.0),
            "route_mix": {"affinity": 94, "spill": 0, "cold": 6,
                          "dense": 0, "failover": 0, "hit_rate": 0.94},
            "scene_homes": {"s0": ["r0"]},
            "replica_cache": {"r0": {"hits": 10, "misses": 0,
                                     "hit_rate": 1.0}},
            "zipf_a": 1.1,
        },
        "wedge_drill": {
            "wedged_replica": "r0", "offered_rps": 1500.0,
            "summary": point(0.5, 1500.0),
            "fleet_totals": {"offered": 100, "served": 100, "shed": 0,
                             "expired": 0, "degraded": 0, "failed": 0,
                             "pending": 0},
            "accounting_exact": True,
            "quarantined": {"r0": "wedge-class fault"},
            "healthy_scene_goodput_retention": 1.0,
            "failed_over_requests": 12,
            "failover_p50_ms": 60.0, "failover_p99_ms": 120.0,
            "failover_bit_identical": True,
            "injector_stats": {
                "r0": {"tag": "r0", "stalls": 1, "failures": 0,
                       "dispatch_unmatched": 0},
                "r1": {"tag": "r1", "stalls": 0, "failures": 0,
                       "dispatch_unmatched": 5},
            },
        },
        "compiled_programs": {"before_load": 3, "after_drill": 3,
                              "hot_path_recompiles": 0},
        "lock_witness": {"edges_observed": {
            "FleetRouter._lock->CounterVec._lock": 10,
            "MicroBatchDispatcher._lock->CounterVec._lock": 10,
        }, "committed_graph_present": True, "violations": [],
            "observed_subgraph_of_committed": True},
        "fault_taxonomy": {
            "observed": {"DispatchStalledError->failed": 1},
            "error_free_outcomes": {"served": 200},
            "violations": [],
            "committed_errors": 13, "committed_edges": 8,
        },
        "obs_snapshot": {"obs_schema": 1, "metrics": {}, "collectors": {}},
        "note": "canned",
    }


def test_fleet_main_emits_one_json_line_and_artifact(tmp_path, monkeypatch,
                                                     capsys):
    """The driver contract: ONE parseable JSON line, headline = healthy
    goodput retention under the wedge, the affinity/failover/recompile
    acceptance fields surfaced, and the .fleet_serve.json artifact with
    platform + recorded_at + obs provenance."""
    monkeypatch.setattr(bench, "_FLEET_FILE", tmp_path / "fleet.json")
    monkeypatch.setattr(
        bench, "measure_on_device",
        lambda *a, **k: {"fleet": _canned_fleet(), "platform": "tpu",
                         "device_kind": "fake-tpu"},
    )
    bench._fleet_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1, f"expected ONE JSON line, got {len(lines)}"
    out = json.loads(lines[0])
    assert out["metric"] == "fleet_healthy_goodput_retention_under_wedge"
    assert out["value"] == 1.0
    assert out["unit"] == "goodput_ratio"
    assert "vs_baseline" in out
    assert out["accounting_exact"] is True
    assert out["affinity_hit_rate"] == 0.94
    assert out["failover_bit_identical"] is True
    assert out["hot_path_recompiles"] == 0
    assert out["knee_sustained_hyps_per_s_by_replicas"] == {
        "1": 3200.0, "2": 6400.0, "3": 9600.0,
    }
    assert "contention" in out
    artifact = json.loads((tmp_path / "fleet.json").read_text())
    assert artifact["platform"] == "tpu"
    assert "recorded_at" in artifact
    # The fleet payload embeds its obs snapshot -> provenance says so.
    assert artifact["obs_provenance"]["has_fleet_snapshot"] is True


def test_fleet_cpu_fallback_carries_provenance(tmp_path, monkeypatch,
                                               capsys):
    """Relay wedged -> the fleet bench measures on CPU and SAYS so."""
    monkeypatch.setattr(bench, "_FLEET_FILE", tmp_path / "fleet.json")
    monkeypatch.setattr(bench, "measure_on_device", lambda *a, **k: None)
    monkeypatch.setattr(bench, "_measure_fleet",
                        lambda *a, **k: _canned_fleet())
    bench._fleet_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert "CPU" in out["note"] or "cpu" in out["note"]
    artifact = json.loads((tmp_path / "fleet.json").read_text())
    assert artifact["platform"] == "cpu"
    assert artifact["note"] == out["note"]


def test_fleet_artifact_schema_committed():
    """The committed .fleet_serve.json satisfies the ISSUE 14 acceptance
    schema: the injected mid-load wedge converted to a typed quarantine
    + failover with every request in exactly ONE outcome class and
    fleet accounting summing exactly to offered (per point and for the
    drill), healthy-scene goodput >= 0.99 through the fault, failover
    results bit-identical to the surviving replica's direct dispatch,
    zero hot-path recompiles, the affinity-hit rate reported under the
    Zipf trace, and per-replica accounting sums in the embedded fleet
    snapshot."""
    import pathlib

    path = pathlib.Path(bench.__file__).parent / ".fleet_serve.json"
    if not path.exists():
        import pytest

        pytest.skip("no committed fleet artifact yet")
    artifact = json.loads(path.read_text())
    for key in ("metric", "value", "unit", "platform", "recorded_at",
                "fleet"):
        assert key in artifact, key
    fleet = artifact["fleet"]
    for leg in fleet["knee_vs_replicas"]:
        for p in leg["points"]:
            assert sum(p["outcomes"].values()) == p["offered"], p
            assert p["accounting_exact"] is True
    drill = fleet["wedge_drill"]
    t = drill["fleet_totals"]
    assert (t["served"] + t["shed"] + t["expired"] + t["degraded"]
            + t["failed"] + t["pending"] == t["offered"])
    assert drill["accounting_exact"] is True
    assert drill["healthy_scene_goodput_retention"] >= 0.99
    assert drill["wedged_replica"] in drill["quarantined"]
    assert drill["failed_over_requests"] >= 1
    assert drill["failover_p99_ms"] is not None
    assert drill["failover_bit_identical"] is True
    # ISSUE 15: always-on sampled causal tracing rode the drill —
    # every sampled trace telescopes exactly at fleet scope, and the
    # exemplar slow traces ride the artifact.
    tr = drill["traces"]
    assert tr["sample_1_in"] >= 1 and tr["sampled"] > 0
    assert tr["telescoping_exact"] is True
    assert tr["max_abs_residual_s"] < 1e-6
    assert tr["exemplar_slow_traces"]
    # The injected fault landed on exactly ONE replica: the target
    # stalled once, every other armed injector only counted unmatched.
    stats = drill["injector_stats"]
    assert stats[drill["wedged_replica"]]["stalls"] == 1
    for name, s in stats.items():
        if name != drill["wedged_replica"]:
            assert s["stalls"] == 0 and s["failures"] == 0
    assert fleet["compiled_programs"]["hot_path_recompiles"] == 0
    assert 0.0 < fleet["affinity"]["route_mix"]["hit_rate"] <= 1.0
    # Runtime lock witness rode the bench, violation-free.
    lw = fleet["lock_witness"]
    assert lw["committed_graph_present"] is True
    assert lw["violations"] == []
    assert lw["observed_subgraph_of_committed"] is True
    assert any(k.startswith("FleetRouter._lock->")
               for k in lw["edges_observed"]), lw["edges_observed"]
    # graft-audit v5: outcome witness over the whole drill, incl. the
    # forced-failover window — violation-free against the committed
    # .fault_taxonomy.json.
    ft = fleet["fault_taxonomy"]
    assert ft["violations"] == []
    assert ft["committed_errors"] >= 13
    # Per-replica-labelled fleet merge in the embedded obs snapshot,
    # each replica's own books summing exactly.
    snap = fleet["obs_snapshot"]
    if snap.get("collectors", {}).get("fleet"):
        for block in snap["collectors"]["fleet"]["replicas"].values():
            s = block["slo"]
            assert (s["served"] + s["shed"] + s["expired"] + s["degraded"]
                    + s["failed"] + s["pending"] == s["offered"])
    assert artifact["obs_provenance"]["has_fleet_snapshot"] is True


# ---------------- obs driver contract (ISSUE 10) ----------------

def _canned_obs():
    """Minimal-but-complete obs payload: the schema the driver and the
    committed .obs_overhead.json artifact rely on."""
    def leg(wall):
        return {
            "wall_s_median": wall,
            "wall_s_spread": [wall - 0.01, wall, wall + 0.01],
            "requests_per_s": round(24 / wall, 1),
            "hyps_per_s": round(24 * 16 / wall, 1),
            "p50_ms": 8.0, "p99_ms": 11.0,
        }

    return {
        "n_frames": 24, "n_hyps_per_frame": 16, "repeats": 9,
        "tracing_off": leg(0.200),
        "tracing_on": leg(0.202),
        "overhead_pct": 1.0,
        "throughput_ratio_on_over_off": 0.9901,
        "within_3pct": True,
        "compiled_programs": {"before": 1, "after_traced_sweep": 1,
                              "jit_cache_misses_added": 0},
        "span_integrity": {"requests_checked": 24,
                           "max_abs_residual_s": 0.0,
                           "sums_match_e2e": True},
        "stage_p50_ms": {"coalesced": 100.0, "staged": 0.7,
                         "dispatched": 0.1, "device": 6.8, "sliced": 0.1,
                         "served": 0.04},
        "snapshot_json_ok": True,
        "fleet": {
            "replicas": 2, "n_frames": 24, "repeats": 9,
            "tracing_off": {"wall_s_median": 0.30,
                            "wall_s_spread": [0.29, 0.30, 0.31],
                            "requests_per_s": 80.0},
            "tracing_on": {"wall_s_median": 0.303,
                           "wall_s_spread": [0.30, 0.303, 0.31],
                           "requests_per_s": 79.2},
            "overhead_pct": 1.0,
            "pair_wall_ratios": [0.99, 1.01, 1.02],
            "throughput_ratio_on_over_off": 0.9901,
            "within_3pct": True,
            "jit_cache_misses_added": 0,
            "telescoping": {
                "traces_checked": 24, "max_abs_residual_s": 0.0,
                "sums_match_e2e": True,
                "failover": {
                    "checked": True, "served": True, "residual_s": 0.0,
                    "sums_match_e2e": True,
                    "root_stages": ["routing", "replica",
                                    "failover_routing", "replica",
                                    "served"],
                    "dispatch_spans": 2, "retry_linked": True,
                    "wedged_replica": "f0",
                },
            },
            "timeline": {"ticks": 12, "windows_retained": 11,
                         "ring_bounded": True},
            "alerts": {"rules": ["slo_burn_rate"], "events": 0,
                       "quiet": True},
            "exemplar_slow_traces": [],
            "note": "canned",
        },
        "obs_snapshot": {
            "obs_schema": 1, "recorded_at_unix": 0.0,
            "metrics": {}, "collectors": {},
        },
        "note": "canned",
    }


def test_obs_main_emits_one_json_line_and_artifact(tmp_path, monkeypatch, capsys):
    """The driver contract: ONE parseable JSON line, headline = tracing
    overhead with the 3%/zero-cache-miss/span-integrity gates surfaced,
    and the .obs_overhead.json artifact with platform + recorded_at +
    the fleet snapshot riding its obs_provenance block."""
    monkeypatch.setattr(bench, "_OBS_FILE", tmp_path / "obs.json")
    monkeypatch.setattr(
        bench, "measure_on_device",
        lambda *a, **k: {"obs": _canned_obs(), "platform": "tpu",
                         "device_kind": "fake-tpu"},
    )
    bench._obs_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1, f"expected ONE JSON line, got {len(lines)}"
    out = json.loads(lines[0])
    assert out["metric"] == "obs_tracing_overhead_pct"
    assert out["value"] == 1.0
    assert out["unit"] == "%"
    assert "vs_baseline" in out
    assert out["within_3pct"] is True
    assert out["jit_cache_misses_added"] == 0
    assert out["span_sums_match_e2e"] is True
    assert out["snapshot_json_ok"] is True
    # ISSUE 15: the fleet leg's gates ride the one JSON line too.
    assert out["fleet_within_3pct"] is True
    assert out["fleet_jit_cache_misses_added"] == 0
    assert out["fleet_telescoping_ok"] is True
    assert out["fleet_overhead_pct"] == 1.0
    assert out["device_kind"] == "fake-tpu"
    assert "contention" in out
    artifact = json.loads((tmp_path / "obs.json").read_text())
    assert artifact["platform"] == "tpu"
    assert "recorded_at" in artifact
    prov = artifact["obs_provenance"]
    assert prov["obs_schema"] == 1
    assert prov["has_fleet_snapshot"] is True
    assert prov["fleet"]["obs_schema"] == 1


def test_obs_cpu_fallback_carries_provenance(tmp_path, monkeypatch, capsys):
    """Relay wedged -> the gate measures on CPU and SAYS so: note field
    on the JSON line, platform "cpu" in the artifact."""
    monkeypatch.setattr(bench, "_OBS_FILE", tmp_path / "obs.json")
    monkeypatch.setattr(bench, "measure_on_device", lambda *a, **k: None)
    monkeypatch.setattr(bench, "_measure_obs", lambda *a, **k: _canned_obs())
    bench._obs_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert "CPU" in out["note"] or "cpu" in out["note"]
    artifact = json.loads((tmp_path / "obs.json").read_text())
    assert artifact["platform"] == "cpu"
    assert artifact["note"] == out["note"]


def test_every_scaffold_artifact_carries_obs_provenance(tmp_path, monkeypatch, capsys):
    """ISSUE 10 satellite: the ONE _driver_main scaffold embeds the obs
    provenance block in EVERY artifact it writes — asserted here through
    a non-obs mode (the canned loadtest), whose payload carries no fleet
    snapshot, so the block records schema-only provenance."""
    monkeypatch.setattr(bench, "_LOADTEST_FILE", tmp_path / "loadtest.json")
    monkeypatch.setattr(
        bench, "measure_on_device",
        lambda *a, **k: {"loadtest": _canned_loadtest(), "platform": "tpu",
                         "device_kind": "fake-tpu"},
    )
    bench._loadtest_main([], [0.0, 0.0, 0.0])
    capsys.readouterr()
    artifact = json.loads((tmp_path / "loadtest.json").read_text())
    prov = artifact["obs_provenance"]
    assert prov["obs_schema"] == 1
    assert prov["has_fleet_snapshot"] is False
    assert "fleet" not in prov


def test_obs_artifact_schema_committed():
    """The committed .obs_overhead.json satisfies the acceptance gates:
    tracing-on throughput within 3% of off, zero added jit cache misses,
    every traced request's span durations summing to its end-to-end
    latency, a json-dumpable embedded fleet snapshot — and, since ISSUE
    15, the FLEET leg: tracing+timeline through a FleetRouter over 2
    replicas within the same 3% pair-median gate, zero jit cache
    misses, the fleet telescoping sum exact (router + replica spans +
    failover siblings == e2e) including across the forced failover
    drill, the timeline ring bounded and a quiet rule catalog."""
    import pathlib

    path = pathlib.Path(bench.__file__).parent / ".obs_overhead.json"
    if not path.exists():
        import pytest

        pytest.skip("no committed obs artifact yet")
    artifact = json.loads(path.read_text())
    for key in ("metric", "value", "unit", "platform", "recorded_at",
                "obs", "obs_provenance"):
        assert key in artifact, key
    obs = artifact["obs"]
    assert obs["within_3pct"] is True
    assert obs["throughput_ratio_on_over_off"] >= 0.97
    assert obs["compiled_programs"]["jit_cache_misses_added"] == 0
    assert obs["span_integrity"]["sums_match_e2e"] is True
    assert obs["span_integrity"]["max_abs_residual_s"] < 1e-6
    assert obs["snapshot_json_ok"] is True
    for legname in ("tracing_off", "tracing_on"):
        leg = obs[legname]
        assert leg["hyps_per_s"] > 0 and leg["p99_ms"] >= leg["p50_ms"]
    snap = obs["obs_snapshot"]
    json.dumps(snap)
    assert snap["obs_schema"] == 1
    assert "serve_stage_seconds" in snap["metrics"]
    assert artifact["obs_provenance"]["fleet"]["obs_schema"] == 1
    # ---- ISSUE 15 fleet leg (the acceptance gate) ----
    fleet = obs["fleet"]
    assert fleet["replicas"] == 2
    assert fleet["within_3pct"] is True
    assert fleet["throughput_ratio_on_over_off"] >= 0.97
    assert fleet["jit_cache_misses_added"] == 0
    tele = fleet["telescoping"]
    assert tele["traces_checked"] > 0
    assert tele["sums_match_e2e"] is True
    assert tele["max_abs_residual_s"] < 1e-6
    fo = tele["failover"]
    assert fo["checked"] is True and fo["served"] is True
    assert fo["sums_match_e2e"] is True and fo["residual_s"] < 1e-6
    assert fo["dispatch_spans"] == 2 and fo["retry_linked"] is True
    assert "failover_routing" in fo["root_stages"]
    assert fleet["timeline"]["ring_bounded"] is True
    assert fleet["timeline"]["ticks"] > 0
    assert fleet["alerts"]["quiet"] is True
    # Exemplar slow traces ride the artifact, json-clean.
    json.dumps(fleet["exemplar_slow_traces"])
    assert fleet["exemplar_slow_traces"]
    assert all(t["residual_s"] < 1e-6
               for t in fleet["exemplar_slow_traces"])


# ---------------- prefetch / weight-tier driver contract (ISSUE 13) ----

def _canned_prefetch():
    """Minimal-but-complete prefetch payload: the schema the driver and
    the committed .weight_tiers.json artifact rely on."""
    def leg(p50, p99, classes, tier=None, pf=None):
        n = 240
        return {
            "served_p50_ms": p50, "served_p99_ms": p99,
            "served_mean_ms": p50, "wall_s": n * p50 / 1e3,
            "outcomes": {"offered": n, "served": n, "shed": 0,
                         "expired": 0, "degraded": 0, "failed": 0,
                         "pending": 0},
            "sums_to_offered": True,
            "fault_classes": classes,
            "cache_stats": {"hits": classes["device_hits"]},
            "tier_stats": tier, "prefetch_stats": pf,
            "compiled_programs": 1, "recompiles_during_trace": 0,
        }

    tier = {"compression": "bf16", "hits": 120, "misses": 12,
            "admissions": 12, "evictions": 0, "purges": 0,
            "resident": 12, "bytes_in_use": 1 << 20,
            "budget_bytes": None, "load_failures": 0,
            "loads_in_flight": 0}
    pf = {"issued_device": 20, "issued_host": 2, "hits": 18, "wasted": 1,
          "failures": 0, "cycles": 900, "in_credit": 1,
          "tracked_scenes": 12, "pending_arrivals": 0}
    return {
        "scenes": {"n": 12, "hw": [24, 24], "num_experts": 2,
                   "n_hyps": 4, "scene_nbytes": 40000},
        "device_budget_bytes": 120001, "device_budget_scenes": 3,
        "hbm_oversubscription_x": 4.0, "zipf_alpha": 1.1,
        "requests_per_leg": 240, "compression": "bf16",
        "legs": {
            "on_demand": leg(25.0, 31.0, {"device_hits": 110,
                                          "host_hits": 0,
                                          "disk_loads": 130,
                                          "demotions": 0}),
            "host_tier": leg(4.0, 7.0, {"device_hits": 110,
                                        "host_hits": 118,
                                        "disk_loads": 12,
                                        "demotions": 127}, tier=tier),
            "host_tier_prefetch": leg(3.3, 6.0, {"device_hits": 120,
                                                 "host_hits": 125,
                                                 "disk_loads": 12,
                                                 "demotions": 133},
                                      tier=tier, pf=pf),
        },
        "p99_cut_x_host_tier": 4.43, "p99_cut_x_prefetch": 5.17,
        "p50_cut_x_prefetch": 7.58,
        "obs_snapshot": None,
        "note": "canned",
    }


def test_prefetch_main_emits_one_json_line_and_artifact(tmp_path, monkeypatch, capsys):
    """The driver contract: ONE parseable JSON line, headline = the p99
    cut of the full hierarchy vs on-demand, accounting/recompile gates
    surfaced, and the .weight_tiers.json artifact with platform +
    recorded_at."""
    monkeypatch.setattr(bench, "_PREFETCH_FILE", tmp_path / "tiers.json")
    monkeypatch.setattr(
        bench, "measure_on_device",
        lambda *a, **k: {"prefetch": _canned_prefetch(), "platform": "tpu",
                         "device_kind": "fake-tpu"},
    )
    bench._prefetch_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1, f"expected ONE JSON line, got {len(lines)}"
    out = json.loads(lines[0])
    assert out["metric"] == "weight_tier_served_p99_cut_x"
    assert out["value"] == 5.17
    assert out["unit"] == "x"
    assert "vs_baseline" in out
    assert out["accounting_exact"] is True
    assert out["recompiles"] == 0
    assert out["hbm_oversubscription_x"] == 4.0
    assert out["device_kind"] == "fake-tpu"
    assert "contention" in out
    artifact = json.loads((tmp_path / "tiers.json").read_text())
    assert artifact["platform"] == "tpu"
    assert "recorded_at" in artifact
    assert set(artifact["prefetch"]["legs"]) == {
        "on_demand", "host_tier", "host_tier_prefetch",
    }


def test_prefetch_cpu_fallback_carries_provenance(tmp_path, monkeypatch, capsys):
    """Relay wedged -> the sweep measures on CPU and SAYS so: note field
    on the JSON line, platform "cpu" in the artifact."""
    monkeypatch.setattr(bench, "_PREFETCH_FILE", tmp_path / "tiers.json")
    monkeypatch.setattr(bench, "measure_on_device", lambda *a, **k: None)
    monkeypatch.setattr(bench, "_measure_prefetch",
                        lambda *a, **k: _canned_prefetch())
    bench._prefetch_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert "CPU" in out["note"] or "cpu" in out["note"]
    artifact = json.loads((tmp_path / "tiers.json").read_text())
    assert artifact["platform"] == "cpu"
    assert artifact["note"] == out["note"]


def test_prefetch_artifact_schema_committed():
    """The committed .weight_tiers.json satisfies the acceptance gates
    (ISSUE 13): HBM oversubscribed >= 4x, per-leg outcome classes sum
    exactly to offered, zero recompiles across all tier transitions in
    every leg, the full hierarchy cuts served p99 vs on-demand >= 3x,
    and the host-tier legs genuinely re-route faults (host hits > 0,
    disk loads collapse vs on-demand)."""
    import pathlib

    path = pathlib.Path(bench.__file__).parent / ".weight_tiers.json"
    if not path.exists():
        import pytest

        pytest.skip("no committed weight-tier artifact yet")
    artifact = json.loads(path.read_text())
    for key in ("metric", "value", "unit", "platform", "recorded_at",
                "prefetch", "obs_provenance"):
        assert key in artifact, key
    pf = artifact["prefetch"]
    assert pf["hbm_oversubscription_x"] >= 4.0
    legs = pf["legs"]
    assert set(legs) == {"on_demand", "host_tier", "host_tier_prefetch"}
    for name, leg in legs.items():
        t = leg["outcomes"]
        assert (t["served"] + t["shed"] + t["expired"] + t["degraded"]
                + t["failed"] + t["pending"] == t["offered"]), name
        assert leg["sums_to_offered"] is True
        assert t["offered"] == pf["requests_per_leg"]
        assert leg["recompiles_during_trace"] == 0, name
        assert leg["compiled_programs"] == 1, name
    assert legs["on_demand"]["fault_classes"]["host_hits"] == 0
    for name in ("host_tier", "host_tier_prefetch"):
        fc = legs[name]["fault_classes"]
        assert fc["host_hits"] > 0, name
        assert fc["disk_loads"] < \
            legs["on_demand"]["fault_classes"]["disk_loads"], name
        assert fc["demotions"] > 0, name
    # The acceptance headline: the full hierarchy's measured p99 cut.
    assert pf["p99_cut_x_prefetch"] >= 3.0
    assert artifact["value"] == pf["p99_cut_x_prefetch"]
    # The prefetcher genuinely decided things and published them.
    stats = legs["host_tier_prefetch"]["prefetch_stats"]
    assert stats["issued_device"] + stats["issued_host"] > 0
    assert stats["cycles"] > 0
    # The embedded fleet snapshot carries the per-tier collectors.
    snap = pf["obs_snapshot"]
    if snap is not None:
        json.dumps(snap)
        assert "host_tier" in snap["collectors"]
        assert "prefetch" in snap["collectors"]


def test_registry_artifact_carries_host_tier_class():
    """The committed .registry_swap.json carries the cold/warm/host-hit
    latency triple (ISSUE 13 satellite): the host-tier hit class exists,
    sits well under the disk cold-load class, and the derived ratios are
    consistent."""
    import pathlib

    path = pathlib.Path(bench.__file__).parent / ".registry_swap.json"
    if not path.exists():
        import pytest

        pytest.skip("no committed registry artifact yet")
    artifact = json.loads(path.read_text())
    reg = artifact["registry"]
    for key in ("cold_load_ms", "warm_hit_ms", "host_tier_hit_ms",
                "host_tier_hit_spread_ms", "host_tier_compression",
                "host_over_warm_x", "cold_over_host_x"):
        assert key in reg, key
    # The class ordering the tier hierarchy sells: warm <= host << cold.
    assert reg["host_tier_hit_ms"] < reg["cold_load_ms"]
    assert reg["cold_over_host_x"] > 1.0
    assert reg["host_tier_compression"] in ("none", "bf16", "int8")


# ---------------- hostpath driver contract (ISSUE 17) ----------------

def _canned_hostpath():
    """Minimal-but-complete hostpath payload: the schema the driver and
    the committed .hostpath.json artifact rely on."""
    def stage(mean, share):
        return {"count": 300, "mean_ms": mean, "p50_ms": mean,
                "p99_ms": 2 * mean, "share": share}

    return {
        "operating_point": {"hw": [24, 24], "num_experts": 2, "n_hyps": 4,
                            "frame_bucket": 2, "scenes": 2,
                            "serve_max_wait_ms": 0.0},
        "requests": 300,
        "closed_loop_rps_traced_path": 400.0,
        "stage_table": {
            "coalesced": stage(0.08, 0.03), "staged": stage(0.8, 0.34),
            "dispatched": stage(0.75, 0.33), "device": stage(0.53, 0.23),
            "sliced": stage(0.1, 0.05), "served": stage(0.05, 0.02),
        },
        "host_overhead": {"host_ms_per_request_mean": 1.8,
                          "device_ms_per_request_mean": 0.5,
                          "host_share": 0.77},
        "capacity": {
            "closed_loop_dispatch_ms": 2.0,
            "per_replica_capacity_rps": 1000.0,
            "reps": 5,
            "committed_baseline_rps": bench.HOSTPATH_BASELINE_RPS,
            "speedup_x_vs_committed": round(
                1000.0 / bench.HOSTPATH_BASELINE_RPS, 3),
            "gate_1p3x": True,
        },
        "accounting": {"offered": 301, "served": 301, "shed": 0,
                       "expired": 0, "degraded": 0, "failed": 0,
                       "pending": 0},
        "accounting_exact": True,
        "compiled_programs": {"before": 1, "after": 1,
                              "hot_path_recompiles": 0},
        "gc": {"frozen": True, "collections_during_run": [3, 0, 0]},
        "platform": "cpu",
    }


def test_hostpath_main_emits_one_json_line_and_artifact(tmp_path,
                                                        monkeypatch, capsys):
    """The driver contract: ONE parseable JSON line on stdout, headline =
    measured capacity with the committed-baseline speedup + 1.3x gate,
    and the .hostpath.json artifact with platform + recorded_at."""
    monkeypatch.setattr(bench, "_HOSTPATH_FILE", tmp_path / "hostpath.json")
    monkeypatch.setattr(
        bench, "measure_on_device",
        lambda *a, **k: {"hostpath": _canned_hostpath(), "platform": "cpu",
                         "device_kind": "cpu"},
    )
    bench._hostpath_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1, f"expected ONE JSON line, got {len(lines)}"
    out = json.loads(lines[0])
    assert out["metric"] == "hostpath_per_replica_capacity_rps"
    assert out["value"] == 1000.0
    assert out["unit"] == "rps"
    assert out["vs_baseline"] == round(
        1000.0 / bench.HOSTPATH_BASELINE_RPS, 3)
    assert out["gate_1p3x_vs_committed"] is True
    assert out["hot_path_recompiles"] == 0
    assert out["accounting_exact"] is True
    assert "contention" in out
    artifact = json.loads((tmp_path / "hostpath.json").read_text())
    assert "recorded_at" in artifact
    assert artifact["hostpath"]["gc"]["frozen"] is True


def test_hostpath_cpu_fallback_carries_provenance(tmp_path, monkeypatch,
                                                  capsys):
    """Relay wedged -> the profile measures on CPU and SAYS so (the leg is
    CPU-by-design, but the scaffold's provenance contract still holds)."""
    monkeypatch.setattr(bench, "_HOSTPATH_FILE", tmp_path / "hostpath.json")
    monkeypatch.setattr(bench, "measure_on_device", lambda *a, **k: None)
    monkeypatch.setattr(bench, "_measure_hostpath",
                        lambda *a, **k: _canned_hostpath())
    bench._hostpath_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert "CPU" in out["note"] or "cpu" in out["note"]
    artifact = json.loads((tmp_path / "hostpath.json").read_text())
    assert artifact["platform"] == "cpu"
    assert artifact["note"] == out["note"]


def test_hostpath_artifact_schema_committed():
    """The committed .hostpath.json (when present) satisfies the ISSUE 17
    evidence schema: a stage table whose shares cover the wall, exact
    outcome accounting, the >= 1.3x capacity gate vs the committed
    baseline, zero hot-path recompiles, and gc provenance."""
    import pathlib

    path = pathlib.Path(bench.__file__).parent / ".hostpath.json"
    if not path.exists():
        import pytest

        pytest.skip("no committed hostpath artifact yet")
    artifact = json.loads(path.read_text())
    for key in ("metric", "value", "unit", "platform", "recorded_at",
                "hostpath"):
        assert key in artifact, key
    hp = artifact["hostpath"]
    # Stage table: every stage carries the full stat row; shares sum ~1.
    shares = [s["share"] for s in hp["stage_table"].values()]
    assert abs(sum(shares) - 1.0) < 0.02
    for s in hp["stage_table"].values():
        for k in ("count", "mean_ms", "p50_ms", "p99_ms", "share"):
            assert k in s, k
    # Accounting sums exactly.
    t = hp["accounting"]
    assert sum(t[o] for o in ("served", "shed", "expired", "degraded",
                              "failed")) + t["pending"] == t["offered"]
    assert hp["accounting_exact"] is True
    # The ISSUE 17 acceptance gate, against the committed baseline.
    cap = hp["capacity"]
    assert cap["committed_baseline_rps"] == bench.HOSTPATH_BASELINE_RPS
    assert cap["gate_1p3x"] is True
    assert cap["per_replica_capacity_rps"] >= \
        1.3 * bench.HOSTPATH_BASELINE_RPS
    assert hp["compiled_programs"]["hot_path_recompiles"] == 0
    assert hp["gc"]["frozen"] is True
    assert len(hp["gc"]["collections_during_run"]) == 3


# ---------------- city retrieval driver contract (ISSUE 18) ----------------

def _canned_city():
    """Minimal-but-complete city payload: the schema the driver and the
    committed .city_retrieval.json artifact rely on."""
    def leg(k, recall):
        return {
            "top_k": k,
            "offered": 30,
            "outcomes": {"served": 25, "shed": 5},
            "by_mix": {
                "easy": {"offered": 16, "served": 16},
                "hard": {"offered": 8, "served": 7, "shed": 1},
                "junk": {"offered": 6, "served": 2, "shed": 4},
            },
            "recall_at_k": recall, "recall_hits": round(recall * 24),
            "retrieval_top1_acc": 0.875,
            "winner_accuracy_served": 0.8,
            "served_p50_ms": 40.0, "served_p99_ms": 120.0,
            "accounting_exact": True, "fleet_accounting_exact": True,
            "bit_identical": True,
            "front": {"offered": 30, "served": 25, "shed": 5,
                      "expired": 0, "degraded": 0, "failed": 0,
                      "pending": 0},
        }

    return {
        "scenes": {"n": 24, "hw": [16, 16], "num_experts": 2,
                   "n_hyps": 4, "frame_bucket": 1},
        "replicas": 2,
        "retriever": {"embed_dim": 16, "max_scenes": 32,
                      "channels": [4, 8], "temperature": 0.1,
                      "train_steps": 200, "train_s": 2.0,
                      "final_loss": 0.1, "enroll_refs_per_scene": 4},
        "calibration": {"min_confidence": 0.45, "easy_top1_p_p5": 0.97,
                        "hard_top1_p_p5": 0.6, "junk_top1_p_p50": 0.33,
                        "junk_top1_p_p95": 0.72},
        "weight_cache": {"budget_bytes": 600000, "scene_bytes": 100000,
                         "oversubscription_x": 4.0,
                         "resident_scenes_max": 6},
        "closed_loop_dispatch_ms": 40.0,
        "deadline_ms": 8000.0, "watchdog_ms": 500.0,
        "query_mix": {"easy": 16, "hard": 8, "junk": 6,
                      "easy_noise": 0.05, "hard_noise": 0.35},
        "legs": [leg(1, 0.7917), leg(2, 0.8333), leg(4, 0.875)],
        "probes": {
            "breaker": {"tripped_scene": "s0", "winner_before": "s0",
                        "candidates_before": ["s0", "s1"],
                        "candidates_tripped": ["s1", "s2"],
                        "tripped_excluded": True,
                        "tripped_skipped_delta": 1,
                        "released_everywhere": True,
                        "bit_identical_restore": True},
            "exhausted": {"raised": True,
                          "type": "RetrievalCandidatesExhaustedError",
                          "retryable": True,
                          "wire_name": "retrieval_candidates_exhausted"},
        },
        "posterior_prefetch_feeds": {"r0": 80, "r1": 80},
        "compiled_programs": {"before_load": 4, "after_drill": 4,
                              "hot_path_recompiles": 0},
        "lock_witness": {"edges_observed": {
            "FleetRouter._lock->CounterVec._lock": 10,
        }, "committed_graph_present": True, "violations": [],
            "observed_subgraph_of_committed": True},
        "fault_taxonomy": {
            "observed": {"RetrievalCandidatesExhaustedError->failed": 1,
                         "RetrievalMissError->shed": 5},
            "error_free_outcomes": {"served": 80},
            "violations": [],
            "committed_errors": 15, "committed_edges": 10,
        },
        "gc": {"frozen": True, "collections_during_run": [0, 0, 0]},
        "obs_snapshot": {"obs_schema": 1, "metrics": {}, "collectors": {}},
        "traces": {"sample_1_in": 8, "sampled": 12,
                   "max_abs_residual_s": 0.0, "telescoping_exact": True,
                   "exemplar_slow_traces": []},
        "note": "canned",
    }


def test_city_main_emits_one_json_line_and_artifact(tmp_path, monkeypatch,
                                                    capsys):
    """The driver contract: ONE parseable JSON line, headline = recall@2
    with the recall-by-K sweep, the accounting / bit-identity /
    recompile acceptance fields surfaced, and the .city_retrieval.json
    artifact with platform + recorded_at + obs provenance."""
    monkeypatch.setattr(bench, "_CITY_FILE", tmp_path / "city.json")
    monkeypatch.setattr(
        bench, "measure_on_device",
        lambda *a, **k: {"city": _canned_city(), "platform": "tpu",
                         "device_kind": "fake-tpu"},
    )
    bench._city_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1, f"expected ONE JSON line, got {len(lines)}"
    out = json.loads(lines[0])
    assert out["metric"] == "city_recall_at_2"
    assert out["value"] == 0.8333
    assert out["unit"] == "recall"
    assert "vs_baseline" in out
    assert out["recall_by_k"] == {"1": 0.7917, "2": 0.8333, "4": 0.875}
    assert out["accounting_exact"] is True
    assert out["breaker_bit_identical_restore"] is True
    assert out["hot_path_recompiles"] == 0
    assert out["min_confidence"] == 0.45
    assert "contention" in out
    artifact = json.loads((tmp_path / "city.json").read_text())
    assert artifact["platform"] == "tpu"
    assert "recorded_at" in artifact
    assert artifact["obs_provenance"]["has_fleet_snapshot"] is True


def test_city_cpu_fallback_carries_provenance(tmp_path, monkeypatch,
                                              capsys):
    """Relay wedged -> the city drill measures on CPU and SAYS so."""
    monkeypatch.setattr(bench, "_CITY_FILE", tmp_path / "city.json")
    monkeypatch.setattr(bench, "measure_on_device", lambda *a, **k: None)
    monkeypatch.setattr(bench, "_measure_city",
                        lambda *a, **k: _canned_city())
    bench._city_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert "CPU" in out["note"] or "cpu" in out["note"]
    artifact = json.loads((tmp_path / "city.json").read_text())
    assert artifact["platform"] == "cpu"
    assert artifact["note"] == out["note"]


def test_city_artifact_schema_committed():
    """The committed .city_retrieval.json (when present) satisfies the
    ISSUE 18 acceptance schema: recall@K for K in {1,2,4} with the
    recall gradient measured on a real ambiguous-query mix, EXACT
    image-tier accounting per leg (front books sum to offered, junk
    included), the confident-query bit-identity pin, the breaker
    fall-through + release_scene bit-identical restore, the typed
    candidates-exhausted probe, zero hot-path recompiles across
    enroll + every leg, and the lock/fault witnesses violation-free."""
    import pathlib

    path = pathlib.Path(bench.__file__).parent / ".city_retrieval.json"
    if not path.exists():
        import pytest

        pytest.skip("no committed city artifact yet")
    artifact = json.loads(path.read_text())
    for key in ("metric", "value", "unit", "platform", "recorded_at",
                "city"):
        assert key in artifact, key
    city = artifact["city"]
    legs = {leg["top_k"]: leg for leg in city["legs"]}
    assert sorted(legs) == [1, 2, 4]
    n_loc = city["query_mix"]["easy"] + city["query_mix"]["hard"]
    for k, leg in legs.items():
        # Exact accounting, both tiers, junk queries included.
        assert sum(leg["outcomes"].values()) == leg["offered"]
        f = leg["front"]
        assert (f["served"] + f["shed"] + f["expired"] + f["degraded"]
                + f["failed"] + f["pending"] == f["offered"])
        assert leg["accounting_exact"] is True
        assert leg["fleet_accounting_exact"] is True
        # recall@K is over ALL localizable queries (misses count
        # against) and the fan-out can never exceed K.
        assert 0.0 <= leg["recall_at_k"] <= 1.0
        assert leg["recall_hits"] <= n_loc
        # Confident-query bit-identity: image-path winner == the same
        # frame dispatched with the winner's scene id.
        assert leg["bit_identical"] is True
    # Wider fan-out never retrieves less (K=1 <= K=2 <= K=4).
    assert legs[1]["recall_at_k"] <= legs[2]["recall_at_k"] + 1e-9
    assert legs[2]["recall_at_k"] <= legs[4]["recall_at_k"] + 1e-9
    # The fleet is genuinely retrievable: recall@4 must beat chance by
    # a wide margin (4/24 scenes ~ 0.17 at random).
    assert legs[4]["recall_at_k"] >= 0.5
    # Breaker fall-through + restore probe.
    br = city["probes"]["breaker"]
    assert br["tripped_excluded"] is True
    assert br["tripped_skipped_delta"] >= 1
    assert br["released_everywhere"] is True
    assert br["bit_identical_restore"] is True
    # Typed exhausted probe on a committed taxonomy edge.
    ex = city["probes"]["exhausted"]
    assert ex["raised"] is True
    assert ex["type"] == "RetrievalCandidatesExhaustedError"
    assert ex["retryable"] is True
    # The no-recompile contract: enroll + three legs + probes never
    # recompiled the retriever or a scene program.
    assert city["compiled_programs"]["hot_path_recompiles"] == 0
    # Posterior-driven prefetch fed every replica's prefetcher.
    assert all(v >= 1 for v in city["posterior_prefetch_feeds"].values())
    # Sampled image traces telescope exactly (retrieval root segment).
    tr = city["traces"]
    assert tr["sampled"] > 0 and tr["telescoping_exact"] is True
    assert tr["max_abs_residual_s"] < 1e-6
    # Runtime witnesses, violation-free against the committed graphs.
    lw = city["lock_witness"]
    assert lw["committed_graph_present"] is True
    assert lw["violations"] == []
    ft = city["fault_taxonomy"]
    assert ft["violations"] == []
    assert ft["committed_errors"] >= 15
    assert city["gc"]["frozen"] is True


# ---------------- sessions driver contract (ISSUE 20) ----------------

def _canned_sessions():
    """Minimal-but-complete sessions payload: the schema the driver and
    the committed .session_serve.json artifact rely on."""
    def point(s, served, shed):
        n = served + shed
        return {
            "sessions": s, "frames_per_session": 16, "offered": n,
            "outcomes": {"served": served, "session_evicted": shed},
            "sums_to_offered": True, "wall_s": 1.0,
            "frames_per_s": float(n), "tracked_frac": 0.9,
            "track_entries": s, "budget_saved_hyps": 100 * s,
            "session_collector_rendered": True, "compiled_programs": 8,
        }

    return {
        "prior_slots": 4,
        "scene": {"hw": [24, 24], "num_experts": 2, "full_n_hyps": 64,
                  "track_n_hyps": 8},
        "parity": {
            "prewarm_compiled_programs": 8,
            "entry": {
                "dense": {"bitwise_equal": True, "prior_hit_any": False},
                "routed_k2": {"bitwise_equal": True,
                              "prior_hit_any": False},
            },
            "dispatcher_bitwise": True,
            "transitions": ["tracked", "lost", "tracked", "lost"],
            "tracked_dispatches": [False, True, False, True],
            "track_losses": 2,
            "recovery_full_budget_next_frame": True,
            "hot_path_recompiles": 0,
            "recompiles_during_flap": 0,
            "typed_errors": {
                "unknown": {"error": "SessionUnknownError",
                            "wire_name": "session_unknown",
                            "retryable": False},
                "evicted": {"error": "SessionEvictedError",
                            "wire_name": "session_evicted",
                            "retryable": True, "is_shed": True},
            },
            "track_loss_trace_events": 2,
        },
        "sequence": {
            "frames": 48, "tracked_frames": 46, "tracked_frac": 0.958,
            "tracked_speedup_x": 2.5, "full_ms_median": 6.0,
            "tracked_ms_median": 2.4, "accuracy_matched": True,
            "prior_hit_frac_tracked": 0.8, "budget_saved_hyps": 10000,
        },
        "recovery": {"corrupted_frame": 24,
                     "loss_transition_at_corruption": True,
                     "fallback_full_budget_next_frame": True,
                     "recovered_within_one_frame": True},
        "loadtest": {"points": [point(2, 32, 0), point(4, 60, 4)],
                     "hot_path_recompiles": 0},
        "lock_witness": {"committed_graph_present": True,
                         "violations": [],
                         "observed_subgraph_of_committed": True,
                         "session_lock_observed": True},
        "fault_taxonomy": {"observed": {"SessionEvictedError->shed": 1},
                           "violations": []},
        "note": "canned",
    }


def test_sessions_main_emits_one_json_line_and_artifact(tmp_path,
                                                        monkeypatch,
                                                        capsys):
    """The driver contract: ONE parseable JSON line on stdout, headline
    = the tracked-vs-full sequence speedup with the parity/recompile/
    recovery acceptance fields surfaced, and the .session_serve.json
    artifact with platform + recorded_at."""
    monkeypatch.setattr(bench, "_SESSIONS_FILE", tmp_path / "sessions.json")
    monkeypatch.setattr(
        bench, "measure_on_device",
        lambda *a, **k: {"sessions": _canned_sessions(), "platform": "tpu",
                         "device_kind": "fake-tpu"},
    )
    bench._sessions_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1, f"expected ONE JSON line, got {len(lines)}"
    out = json.loads(lines[0])
    assert out["metric"] == "session_tracked_speedup_x"
    assert out["value"] == 2.5
    assert out["unit"] == "x"
    assert "vs_baseline" in out
    assert out["parity_bitwise_entry"] is True
    assert out["parity_bitwise_dispatcher"] is True
    assert out["hot_path_recompiles"] == 0
    assert out["recovered_within_one_frame"] is True
    assert out["accounting_exact"] is True
    artifact = json.loads((tmp_path / "sessions.json").read_text())
    assert artifact["platform"] == "tpu"
    assert "recorded_at" in artifact
    assert artifact["sessions"]["prior_slots"] == 4


def test_sessions_cpu_fallback_carries_provenance(tmp_path, monkeypatch,
                                                  capsys):
    """Relay wedged -> the session drill measures on CPU and SAYS so."""
    monkeypatch.setattr(bench, "_SESSIONS_FILE", tmp_path / "sessions.json")
    monkeypatch.setattr(bench, "measure_on_device", lambda *a, **k: None)
    monkeypatch.setattr(bench, "_measure_sessions",
                        lambda *a, **k: _canned_sessions())
    bench._sessions_main([], [0.0, 0.0, 0.0])
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1
    out = json.loads(lines[0])
    assert "CPU" in out["note"] or "cpu" in out["note"]
    artifact = json.loads((tmp_path / "sessions.json").read_text())
    assert artifact["platform"] == "cpu"
    assert artifact["note"] == out["note"]


def test_sessions_artifact_schema_committed():
    """The committed .session_serve.json (when present) satisfies the
    ISSUE 20 acceptance schema: all-invalid parity bitwise at entry
    level (dense AND routed) and through a live dispatcher, zero
    hot-path recompiles across tracked/lost/recovered transitions AND
    across the session loadtest, >= 2x tracked sequence speedup at
    matched pose accuracy, recovery-after-loss within one frame with
    the loss typed + accounted, per-point session outcome classes
    summing exactly to offered, and the lock/fault witnesses
    violation-free."""
    import pathlib

    path = pathlib.Path(bench.__file__).parent / ".session_serve.json"
    if not path.exists():
        import pytest

        pytest.skip("no committed sessions artifact yet")
    artifact = json.loads(path.read_text())
    for key in ("metric", "value", "unit", "platform", "recorded_at",
                "sessions"):
        assert key in artifact, key
    sess = artifact["sessions"]
    par = sess["parity"]
    # The §23 parity pin, entry level (dense AND routed) + dispatcher.
    for leg in par["entry"].values():
        assert leg["bitwise_equal"] is True
        assert leg["prior_hit_any"] is False
    assert set(par["entry"]) >= {"dense"}
    assert any(k.startswith("routed") for k in par["entry"])
    assert par["dispatcher_bitwise"] is True
    # Zero hot-path recompiles, flap drill and loadtest both.
    assert par["hot_path_recompiles"] == 0
    assert par["recompiles_during_flap"] == 0
    assert sess["loadtest"]["hot_path_recompiles"] == 0
    # Every loss was followed by a full-budget recovery dispatch.
    assert par["recovery_full_budget_next_frame"] is True
    assert par["track_losses"] >= 1
    # Typed session errors observed with their committed wire names.
    te = par["typed_errors"]
    assert te["evicted"]["wire_name"] == "session_evicted"
    assert te["evicted"]["retryable"] is True
    assert te["evicted"]["is_shed"] is True
    assert te["unknown"]["wire_name"] == "session_unknown"
    assert te["unknown"]["retryable"] is False
    # The perf acceptance: >= 2x tracked speedup at matched accuracy.
    seq = sess["sequence"]
    assert seq["tracked_speedup_x"] >= 2.0
    assert seq["accuracy_matched"] is True
    assert seq["tracked_frames"] >= seq["frames"] // 2
    assert 0.0 < seq["prior_hit_frac_tracked"] <= 1.0
    assert seq["budget_saved_hyps"] > 0
    # Recovery-after-loss within one frame, typed + accounted.
    rec = sess["recovery"]
    assert rec["loss_transition_at_corruption"] is True
    assert rec["fallback_full_budget_next_frame"] is True
    assert rec["recovered_within_one_frame"] is True
    # Session-level loadtest: exact outcome accounting per point.
    for p in sess["loadtest"]["points"]:
        assert sum(p["outcomes"].values()) == p["offered"]
        assert p["sums_to_offered"] is True
        assert p["session_collector_rendered"] is True
    # Runtime witnesses, violation-free against the committed graphs.
    lw = sess["lock_witness"]
    assert lw["committed_graph_present"] is True
    assert lw["violations"] == []
    assert lw["session_lock_observed"] is True
    assert sess["fault_taxonomy"]["violations"] == []
