"""Tests for the Flax expert / gating networks and the torch converter."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from esac_tpu.models import ExpertNet, GatingNet, coordinate_loss, torch_state_dict_to_flax
from esac_tpu.models.gating import gating_cross_entropy

# Tiny configs keep CPU tests fast.
TINY_EXPERT = dict(stem_channels=(8, 16, 32), head_channels=32, head_depth=2)


def test_expert_output_shape_stride8():
    net = ExpertNet(**TINY_EXPERT)
    x = jnp.zeros((1, 64, 96, 3))
    params = net.init(jax.random.key(0), x)
    y = net.apply(params, x)
    assert y.shape == (1, 8, 12, 3)
    assert y.dtype == jnp.float32


def test_expert_scene_center_offset():
    net = ExpertNet(scene_center=(3.0, 2.0, 1.5), **TINY_EXPERT)
    x = jnp.zeros((1, 32, 32, 3))
    params = net.init(jax.random.key(0), x)
    y = net.apply(params, x)
    # Fresh random init with zero input: output should hover near the center.
    assert np.abs(np.asarray(y).mean(axis=(0, 1, 2)) - np.array([3.0, 2.0, 1.5])).max() < 1.0


def test_expert_reference_size_param_count():
    net = ExpertNet()
    x = jnp.zeros((1, 64, 64, 3))
    params = net.init(jax.random.key(0), x)
    n = sum(p.size for p in jax.tree.leaves(params))
    # Reference expert is ~10^7 params (SURVEY.md §2 #1).
    assert 5e6 < n < 4e7, f"{n} params"


def test_expert_trains_one_step():
    net = ExpertNet(**TINY_EXPERT)
    x = jax.random.uniform(jax.random.key(1), (2, 32, 32, 3))
    target = jax.random.uniform(jax.random.key(2), (2, 4, 4, 3)) * 4.0

    params = net.init(jax.random.key(0), x)

    def loss_fn(p):
        return coordinate_loss(net.apply(p, x), target)

    l0, g = jax.value_and_grad(loss_fn)(params)
    assert jnp.isfinite(l0)
    params2 = jax.tree.map(lambda p, gr: p - 1e-3 * gr, params, g)
    l1 = loss_fn(params2)
    assert l1 < l0


def test_gating_shapes_and_loss():
    net = GatingNet(num_experts=7, channels=(8, 16))
    x = jnp.zeros((3, 64, 64, 3))
    params = net.init(jax.random.key(0), x)
    logits = net.apply(params, x)
    assert logits.shape == (3, 7)
    loss = gating_cross_entropy(logits, jnp.array([0, 3, 6]))
    assert jnp.isfinite(loss)


def test_coordinate_loss_masking():
    pred = jnp.zeros((4, 3))
    target = jnp.ones((4, 3))
    mask = jnp.array([1.0, 1.0, 0.0, 0.0])
    # Unmasked cells each contribute |1|*3; masked ignored.
    assert coordinate_loss(pred, target, mask) == pytest.approx(3.0, abs=1e-5)
    # All-masked: must not divide by zero.
    assert jnp.isfinite(coordinate_loss(pred, target, jnp.zeros(4)))


def test_torch_converter_roundtrip():
    torch = pytest.importorskip("torch")

    class TorchTwin(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.c1 = torch.nn.Conv2d(3, 8, 3, padding=1)
            self.c2 = torch.nn.Conv2d(8, 8, 3, stride=2, padding=1)
            self.fc = torch.nn.Linear(8, 5)

        def forward(self, x):  # NCHW
            import torch.nn.functional as tF

            x = tF.relu(self.c1(x))
            x = tF.relu(self.c2(x))
            x = x.mean(dim=(2, 3))
            return self.fc(x)

    class FlaxTwin(__import__("flax").linen.Module):
        @__import__("flax").linen.compact
        def __call__(self, x):  # NHWC
            import flax.linen as nn

            x = nn.relu(nn.Conv(8, (3, 3))(x))
            # torch padding=1 is symmetric; XLA SAME at stride 2 is not.
            x = nn.relu(nn.Conv(8, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))(x))
            x = x.mean(axis=(1, 2))
            return nn.Dense(5)(x)

    tnet = TorchTwin().eval()
    fnet = FlaxTwin()
    x = np.random.default_rng(0).uniform(size=(2, 16, 16, 3)).astype(np.float32)
    params = fnet.init(jax.random.key(0), jnp.asarray(x))
    converted = {"params": torch_state_dict_to_flax(tnet.state_dict(), params["params"])}
    got = np.asarray(fnet.apply(converted, jnp.asarray(x)))
    with torch.no_grad():
        want = tnet(torch.from_numpy(np.transpose(x, (0, 3, 1, 2)))).numpy()
    np.testing.assert_allclose(got, want, atol=2e-4)


def test_torch_converter_rejects_shape_mismatch():
    torch = pytest.importorskip("torch")
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(x)

    params = Tiny().init(jax.random.key(0), jnp.zeros((1, 8)))
    bad = {"fc.weight": torch.zeros(4, 99), "fc.bias": torch.zeros(4)}
    with pytest.raises(ValueError, match="shape mismatch"):
        torch_state_dict_to_flax(bad, params["params"])


# Tier-1 budget (TODO item 9, ISSUE 17): ~8s CLI wrapper; the converter
# core stays tier-1 via test_torch_converter_roundtrip.
@pytest.mark.slow
def test_convert_checkpoint_cli_gating(tmp_path):
    torch = pytest.importorskip("torch")
    import subprocess, sys, pathlib

    REPO = pathlib.Path(__file__).resolve().parent.parent
    # Torch twin of GatingNet(size=test, experts=3): convs (8,16) x2 + 2 dense.
    layers = [
        torch.nn.Conv2d(3, 8, 3, stride=2, padding=1), torch.nn.Conv2d(8, 8, 3, padding=1),
        torch.nn.Conv2d(8, 16, 3, stride=2, padding=1), torch.nn.Conv2d(16, 16, 3, padding=1),
        torch.nn.Linear(16, 64), torch.nn.Linear(64, 3),
    ]
    sd = torch.nn.Sequential(*layers).state_dict()
    pth = tmp_path / "g.pth"
    torch.save(sd, pth)
    r = subprocess.run(
        [sys.executable, str(REPO / "convert_checkpoint.py"), "gating", str(pth),
         str(tmp_path / "out"), "--size", "test", "--experts", "3",
         "--height", "64", "--width", "64"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert r.returncode == 0, r.stderr
    assert (tmp_path / "out" / "config.json").exists()


def test_stacked_expert_forward_is_scan_not_unrolled():
    """Config #4 compile scaling (VERDICT r1 weak #4): the multi-expert
    forward must lower to one lax.map/scan over stacked params, so the
    traced graph is the same size at M=48 as at M=2 — not 48 unrolled
    copies of the conv graph."""
    import jax
    import jax.numpy as jnp

    from esac_tpu.cli import make_expert

    net = make_expert("test", (0.0, 0.0, 0.0))
    p1 = net.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))

    def stacked(M):
        stack = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (M,) + x.shape), p1
        )
        centers = jnp.zeros((M, 3))
        return stack, centers

    def fwd(stack, centers, images):
        return jax.lax.map(
            lambda pc: net.apply(pc[0], images) + pc[1], (stack, centers)
        )

    images = jnp.zeros((2, 32, 32, 3))
    n2 = len(jax.make_jaxpr(fwd)(*stacked(2), images).eqns)
    n48 = len(jax.make_jaxpr(fwd)(*stacked(48), images).eqns)
    assert n48 == n2
