"""Unit tests for projection / reprojection / pose-error math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from esac_tpu.geometry import (
    pose_errors,
    project,
    reprojection_errors,
    rodrigues,
    transform_points,
)


F = jnp.float32(525.0)
C = jnp.array([320.0, 240.0])


def test_project_center():
    # A point on the optical axis lands on the principal point.
    Y = jnp.array([[0.0, 0.0, 2.0]])
    np.testing.assert_allclose(project(Y, F, C), C[None], atol=1e-6)


def test_project_known_offset():
    Y = jnp.array([[1.0, 0.0, 2.0]])
    expected = jnp.array([[320.0 + 525.0 / 2.0, 240.0]])
    np.testing.assert_allclose(project(Y, F, C), expected, atol=1e-5)


def test_reprojection_zero_for_exact_pose():
    key = jax.random.key(0)
    rvec = jnp.array([0.1, -0.2, 0.05])
    t = jnp.array([0.3, -0.1, 0.2])
    R = rodrigues(rvec)
    X = jax.random.uniform(key, (50, 3), minval=-1.0, maxval=1.0) + jnp.array([0.0, 0.0, 4.0])
    # Scene points placed so all are in front of the camera after transform.
    x2d = project(transform_points(R, t, X), F, C)
    errs = reprojection_errors(R, t, X, x2d, F, C)
    np.testing.assert_allclose(errs, jnp.zeros(50), atol=1e-3)


def test_behind_camera_penalized():
    R = jnp.eye(3)
    t = jnp.zeros(3)
    X = jnp.array([[0.0, 0.0, -2.0]])
    errs = reprojection_errors(R, t, X, C[None], F, C)
    assert errs[0] > 999.0


def test_pose_errors_identity():
    R = rodrigues(jnp.array([0.2, 0.1, -0.3]))
    t = jnp.array([1.0, 2.0, 3.0])
    r_err, t_err = pose_errors(R, t, R, t)
    assert r_err == pytest.approx(0.0, abs=1e-3)
    assert t_err == pytest.approx(0.0, abs=1e-5)


def test_pose_errors_translation_is_camera_center_distance():
    R = jnp.eye(3)
    t1 = jnp.array([0.0, 0.0, 0.0])
    t2 = jnp.array([0.05, 0.0, 0.0])
    _, t_err = pose_errors(R, t1, R, t2)
    assert t_err == pytest.approx(0.05, abs=1e-6)
